"""Headline benchmark: ResNet-50 ImageNet training + transformer-LM MFU.

Reference baseline (BASELINE.md / docs/faq/perf.md:205-215): MXNet 1.2
ResNet-50 training, batch 32, fp32, 1x V100 = 298.51 img/s.

The whole training step — forward, backward, gradient scale, SGD momentum
update — is ONE XLA computation (parallel/trainer.py TrainStep) running
bf16 on the MXU with fp32 master weights (the multi-precision
configuration the reference exposes as optimizer.py SGD multi_precision).
The ResNet trunk runs channel-last (NHWC) end-to-end with the one-pass
fused BatchNorm schedule (ops/nn.py _bn_train_fused) — see docs/PERF.md
for the roofline analysis of why ResNet-50/224 is HBM-bandwidth-bound.

The default run prints ONE JSON line: the ResNet-50 img/s headline plus
``transformer_*`` fields from the arithmetic-intensity-dense
transformer-LM benchmark (models/transformer.py), which demonstrates the
framework reaches MXU-bound MFU when the model is not bandwidth-bound.
Use ``--model resnet|transformer|all`` to select.
"""
import argparse
import json
import os
import time

import numpy as np


BASELINE_IMG_PER_SEC = 298.51


def _step_hist():
    """A fine-grained (factor-1.25 buckets) mx.telemetry Histogram for
    per-step wall times — the latency-distribution source behind the
    ``step_ms_p50``/``step_ms_p99`` JSON fields (docs/OBSERVABILITY.md)."""
    from mxnet_tpu import telemetry
    return telemetry.Histogram(
        "bench_step_ms", unit="ms",
        bounds=telemetry.exponential_buckets(0.01, 1.25, 72))


def _round_opt(v, digits=3):
    return None if v is None else round(v, digits)


def _latency_fields(hist, compile_ms):
    """step_ms_p50 / step_ms_p99 / compile_ms fields every bench mode
    folds into its JSON line. ``compile_ms`` is first-trace wall time
    (trace + XLA compile + first run of the measurement program)."""
    have = hist is not None and hist.count > 0
    return {
        "step_ms_p50": _round_opt(hist.quantile(0.5)) if have else None,
        "step_ms_p99": _round_opt(hist.quantile(0.99)) if have else None,
        "compile_ms": _round_opt(compile_ms, 1),
    }

def _check_sane(achieved, peak):
    """Refuse to report throughput above the chip's physical peak — a
    wedged tunnel/OOM can make the timing loop "complete" instantly."""
    if achieved and peak and achieved > peak:
        raise SystemExit(
            "bench: achieved %.1f TFLOP/s exceeds the %.0f TF peak — "
            "the timing loop did not actually execute (tunnel/OOM "
            "failure); refusing to report garbage" % (achieved, peak))


def _peak_tflops(device_kind):
    """Peak bf16 TFLOP/s — the one table lives in the compiled-program
    registry (telemetry/programs.py PEAK_TFLOPS_TABLE)."""
    from mxnet_tpu import telemetry
    return telemetry.programs.peak_tflops(device_kind)


def _mfu_fields(flops_hand, flops_measured, iters, dt, device_kind):
    """The hand-math vs compiler-measured MFU pair every training bench
    folds into its JSON: ``mfu`` from the analytic FLOP count (the
    numerator docs/PERF.md derives by hand — known to drop attention
    matmuls on the transformer arm), ``mfu_measured`` from XLA
    ``cost_analysis()`` via the compiled-program registry.  A >10%
    FLOP-count disagreement warns on stderr (time cancels, so the
    check runs on the CPU container too) — the measured number is the
    trustworthy one.  Also refreshes the ``mfu_measured`` gauge."""
    import sys
    from mxnet_tpu import telemetry

    peak = _peak_tflops(device_kind)
    sec = dt / iters if iters else None
    ach_hand = (flops_hand / sec / 1e12
                if flops_hand and sec else None)
    ach_meas = (flops_measured / sec / 1e12
                if flops_measured and sec else None)
    _check_sane(ach_meas if ach_meas is not None else ach_hand, peak)
    mfu_hand = (ach_hand / peak) if ach_hand and peak else None
    mfu_meas = (ach_meas / peak) if ach_meas and peak else None
    if flops_hand and flops_measured \
            and abs(flops_hand - flops_measured) > 0.10 * flops_measured:
        print("bench: WARNING hand-math FLOPs/step %.3g disagree with "
              "compiler-measured %.3g by %.0f%% — trust mfu_measured "
              "(the hand numerator is known to drop attention matmuls)"
              % (flops_hand, flops_measured,
                 100.0 * abs(flops_hand - flops_measured)
                 / flops_measured), file=sys.stderr)
    if flops_measured and sec:
        telemetry.programs.mfu_measured(flops_measured, sec, device_kind)
    ach = ach_meas if ach_meas is not None else ach_hand
    mfu = mfu_hand if mfu_hand is not None else mfu_meas
    return {
        "achieved_tflops": round(ach, 2) if ach else None,
        "peak_bf16_tflops": peak,
        "mfu": round(mfu, 4) if mfu else None,
        "mfu_measured": round(mfu_meas, 4) if mfu_meas else None,
        "flops_per_step_hand": flops_hand,
        "flops_per_step_measured": flops_measured,
    }


def _make_pipeline_stream(args, image_shape):
    """Endless DataBatch stream from a generated .rec of JPEG images
    (PrefetchingIter over ImageRecordIter with the native decode path)."""
    import io as _pyio
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import recordio
    from PIL import Image

    c, h, w = image_shape
    n_images = max(2 * args.batch, 256)
    d = tempfile.mkdtemp(prefix="bench_rec_")
    rec_path = d + "/bench.rec"
    idx_path = d + "/bench.idx"
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(n_images):
        img = rng.randint(0, 255, (h, w, c), dtype=np.uint8)
        buf = _pyio.BytesIO()
        Image.fromarray(img.squeeze() if c == 1 else img).save(
            buf, "JPEG", quality=90)
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.getvalue()))
    rec.close()

    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path,
        data_shape=image_shape, batch_size=args.batch, shuffle=True,
        rand_mirror=True, mean_r=127.0, mean_g=127.0, mean_b=127.0,
        std_r=64.0, std_g=64.0, std_b=64.0,
        preprocess_threads=args.decode_threads)
    it = mx.io.PrefetchingIter(it)

    def stream():
        while True:
            it.reset()
            for batch in it:
                yield batch

    return stream()


def _timed_steps(ts, next_batch, warmup, iters):
    """Host-fed timing loop (pipeline mode): warm up, time ``iters``
    python-dispatched steps. The synthetic benches use _fori_timed
    instead (see there for why). Returns ``(dt, info)`` where info
    carries compile_ms (first warm-up step = trace+compile wall time)
    and a per-step latency histogram (host step times incl. data)."""
    import jax
    from mxnet_tpu import telemetry

    compile_ms = None
    for i in range(max(1, warmup)):   # >=1: keep compile out of the
        t0 = time.perf_counter()      # measured (histogrammed) steps
        ts.step(next_batch(i))
        if i == 0:
            jax.block_until_ready(ts.params)
            compile_ms = (time.perf_counter() - t0) * 1e3
            telemetry.JIT_COMPILE_MS.observe(compile_ms)
    jax.block_until_ready(ts.params)

    hist = _step_hist()
    t0 = time.perf_counter()
    for i in range(iters):
        t_s = time.perf_counter()
        ts.step(next_batch(i))
        hist.observe((time.perf_counter() - t_s) * 1e3)
    jax.block_until_ready(ts.params)
    dt = time.perf_counter() - t0

    # liveness guard: force a real readback; a wedged tunnel/OOM can
    # otherwise report instant "completion" and absurd throughput
    import jax.numpy as jnp
    probe_w = float(jnp.asarray(
        next(iter(ts.params.values())).ravel()[0]))
    if not np.isfinite(probe_w):
        raise SystemExit("bench: non-finite weights after timing loop")
    return dt, {"compile_ms": compile_ms, "hist": hist}


def _cost_flops(ts, flops_probe, site="bench_train_step"):
    """Per-step FLOPs from XLA cost analysis (abstract-probe lowering,
    run after timing — a second live executable alongside the timing
    loop has been seen to wedge tunneled harnesses).  The compiled
    probe registers in the compiled-program registry
    (``telemetry.programs()``), which is also where the FLOP number is
    read back from — one analysis pipeline for bench, roofline and the
    flight recorder."""
    if flops_probe is None:
        return None
    try:
        compiled = ts._step_fn.lower(*flops_probe).compile()
    except Exception:
        return None
    try:
        from mxnet_tpu import telemetry
        entry = telemetry.programs.register_compiled(
            site, compiled, fn_name="train_step")
        return float(entry.get("flops") or 0.0) or None
    except Exception:
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            return float(cost.get("flops", 0.0)) or None
        except Exception:
            return None


def _flash_attention_flops(args):
    """Analytic FLOPs of the Pallas flash-attention kernels per step —
    XLA's cost analysis reports 0 for custom calls, so without this the
    MFU numerator silently drops the attention matmuls when the fused
    kernel is active (ops/nn.py _use_flash_attention). Counted causally
    (half the S^2 blocks): forward = QK^T + PV = 2 matmuls, backward =
    score recompute + dV + dP + dQ + dK = 5 matmuls.
    """
    import jax.numpy as jnp
    from mxnet_tpu.ops.nn import _use_flash_attention
    B, S = args.lm_batch, args.lm_seq
    H, D = args.lm_heads, args.lm_d_model // args.lm_heads
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else \
        jnp.dtype(args.dtype)
    if not _use_flash_attention(S, D, dtype):
        return 0.0  # XLA path: cost analysis already counts these
    per_matmul = 2.0 * B * H * S * S * D
    causal = 0.5
    return args.lm_layers * (2 + 5) * per_matmul * causal


def _fori_timed(ts, batches, iters, lr, warmup=1):
    """Time ``iters`` training steps as the DIFFERENCE between one
    (n0+iters)-step and one n0-step program, each a single launch with
    the step chain inside ``lax.fori_loop``.

    Why not a python dispatch loop: on tunneled dev harnesses the
    client has been observed to coalesce per-step launches whose donated
    buffer handles repeat, reporting instant completion and absurd
    throughput (docs/PERF.md). One launch per measurement with a forced
    scalar readback is immune, and the differential cancels the launch +
    readback round trip. On a direct-attached TPU both methods agree.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    if ts._step_fn is None:
        ts._step_fn = ts._build_step()
    step = ts._step_fn
    lr = jnp.float32(lr)

    # the two batches stack into one argument; each step gathers only
    # its slice (a per-step jnp.where select would read both batches
    # and write a copy — measurable extra HBM traffic in an HBM-bound
    # loop). Arguments, not closure constants: baked-in ImageNet
    # batches blow the remote-compile size limit.
    stacked = jax.tree.map(lambda a, b: jnp.stack([a, b]),
                           batches[0], batches[1])

    def make(n):
        @jax.jit
        def run(params, states, auxs, bstack):
            def body(i, carry):
                p, s, a = carry
                batch = jax.tree.map(
                    lambda v: lax.dynamic_index_in_dim(
                        v, i % 2, 0, keepdims=False), bstack)
                p, s, a, _outs = step(p, s, a, batch, lr,
                                      (i + 1).astype(jnp.uint32))
                return (p, s, a)
            return lax.fori_loop(0, n, body, (params, states, auxs))
        return run

    n0 = 2
    short = make(n0)
    long_ = make(n0 + iters)

    def timed(fn):
        t0 = time.perf_counter()
        p, s, a = fn(ts.params, ts.states, ts.auxs, stacked)
        w = float(jnp.asarray(next(iter(p.values())).ravel()[0]))
        if not np.isfinite(w):
            raise SystemExit("bench: non-finite weights in timing loop")
        return time.perf_counter() - t0

    # compile + warm both programs (>= --warmup repetitions), measure.
    # The first calls trace+compile: their wall time is the compile_ms
    # witness (observed into the jit_compile_ms registry histogram too)
    from mxnet_tpu import telemetry
    compile_ms = None
    for i in range(max(1, warmup)):
        t_s = timed(short)
        t_l = timed(long_)
        if i == 0:
            compile_ms = (t_s + t_l) * 1e3
            telemetry.JIT_COMPILE_MS.observe(compile_ms)
    shorts = [timed(short) for _ in range(2)]
    longs = [timed(long_) for _ in range(2)]
    t_short = min(shorts)
    t_long = min(longs)
    # per-step latency distribution: each long-program repetition gives
    # one per-step estimate against the best short baseline (few samples
    # by design — the tunnel forbids per-step dispatch timing, see above)
    hist = _step_hist()
    for t_l in longs:
        est = (t_l - t_short) / iters * 1e3
        if est > 0:
            hist.observe(est)
    dt = t_long - t_short
    if dt <= 0:
        raise SystemExit(
            "bench: non-positive timing differential (%.4fs long vs "
            "%.4fs short) — wall-clock noise exceeded the measured "
            "work; rerun with more --iters" % (t_long, t_short))
    return dt, {"compile_ms": compile_ms, "hist": hist}


def bench_pipeline_scaling(args):
    """Host-side decode-pipeline throughput at 1/2/4/8 threads
    (VERDICT r2 item 5): iterator-only timing (ImageRecordIter native
    libjpeg decode + augment), no device in the loop, so the number
    isolates the input pipeline. On a 1-core harness the curve is flat
    by construction; on a real multi-core TPU host it scales."""
    import mxnet_tpu as mx

    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    saved = args.decode_threads
    rates = {}
    for nthreads in (1, 2, 4, 8):
        args.decode_threads = nthreads
        stream = _make_pipeline_stream(args, image_shape)
        # warm one batch (thread spin-up), then time
        next(stream)
        n_batches = 4
        t0 = time.perf_counter()
        for _ in range(n_batches):
            next(stream)
        dt = time.perf_counter() - t0
        rates[str(nthreads)] = round(args.batch * n_batches / dt, 1)
    args.decode_threads = saved
    best = max(rates.values())
    return {"metric": "pipeline_decode_img_per_sec", "value": best,
            "unit": "img/s", "threads": rates,
            "note": "host decode only; flat on 1-core harnesses"}


def bench_resnet(args):
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import TrainStep

    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    c, h, w = image_shape
    data_shape = ((args.batch, h, w, c) if args.layout == "NHWC"
                  else (args.batch,) + image_shape)
    sym = models.get_symbol("resnet", num_classes=1000,
                            num_layers=args.num_layers,
                            image_shape=image_shape, dtype=args.dtype,
                            layout=args.layout)
    n_fused = 0
    if args.fuse:
        # BN→ReLU→Conv1×1 Pallas fusion (symbol/fuse.py); matches only
        # channel-last 1×1 sites, so it no-ops on NCHW — n_fused is
        # reported so a silent no-op can't masquerade as an A/B arm
        from mxnet_tpu.symbol.fuse import count_fused, fuse_conv_bn
        sym = fuse_conv_bn(sym)
        n_fused = count_fused(sym)
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                           multi_precision=(args.dtype != "float32"),
                           rescale_grad=1.0 / args.batch)
    ts = TrainStep(sym, opt,
                   data_shapes={"data": data_shape},
                   label_shapes={"softmax_label": (args.batch,)})
    ts.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                  magnitude=2))

    rng = np.random.RandomState(0)
    if args.pipeline:
        # real input pipeline: generated .rec of JPEGs through the native
        # threaded decode + augment + prefetch path (NCHW batches per the
        # iterator contract; relayout to NHWC is part of the measured cost)
        stream = _make_pipeline_stream(args, image_shape)

        def next_batch(_i):
            b = next(stream)
            d = b.data[0].asnumpy()
            if args.layout == "NHWC":
                d = np.transpose(d, (0, 2, 3, 1))
            return {"data": d, "softmax_label": b.label[0].asnumpy()}
        dt, lat = _timed_steps(ts, next_batch, args.warmup, args.iters)
        flops_measured = None
    else:
        # Synthetic device-resident batches (the reference's perf.md
        # numbers are synthetic-data benchmarks of the training step).
        batches = []
        for _ in range(2):
            data = jnp.asarray(rng.uniform(-1, 1, data_shape)
                               .astype(np.float32))
            label = jnp.asarray(rng.randint(0, 1000, (args.batch,))
                                .astype(np.float32))
            batches.append({"data": data, "softmax_label": label})
        jax.block_until_ready(batches)

        dt, lat = _fori_timed(ts, batches, args.iters, lr=0.1,
                              warmup=args.warmup)
        # abstract probe: lowering must not touch live (donated) buffers
        probe = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (ts.params, ts.states, ts.auxs, batches[0],
             jnp.float32(0.1), jnp.uint32(0)))
        flops_measured = _cost_flops(ts, probe, site="bench_resnet")
    # hand numerator (docs/PERF.md): ResNet-50 fwd ≈ 4.1 GMACs =
    # 8.2 GFLOP/img; training ≈ 3x fwd — `mfu` reports this, the
    # compiler-measured count reports as `mfu_measured` beside it
    flops_hand = 24.6e9 * args.batch if args.num_layers == 50 else None

    img_per_sec = args.batch * args.iters / dt
    dev = jax.devices()[0]
    return {
        "metric": ("resnet50_train_img_per_sec_pipeline" if args.pipeline
                   else "resnet50_train_img_per_sec"),
        "value": round(img_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "device_kind": dev.device_kind,
        "layout": args.layout,
        "fused": n_fused,
        **_mfu_fields(flops_hand, flops_measured, args.iters, dt,
                      dev.device_kind),
        **_latency_fields(lat["hist"], lat["compile_ms"]),
    }


def bench_transformer(args):
    """Decoder-only LM training throughput (models/transformer.py):
    the MXU-bound benchmark. No reference baseline exists (MXNet 1.2
    predates transformers) — the target is absolute MFU."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import TrainStep

    B, S = args.lm_batch, args.lm_seq
    sym = models.get_symbol("transformer", num_classes=args.lm_vocab,
                            num_layers=args.lm_layers,
                            d_model=args.lm_d_model,
                            num_heads=args.lm_heads, seq_len=S,
                            dtype=args.dtype)
    opt = mx.optimizer.SGD(learning_rate=0.01, momentum=0.9,
                           multi_precision=(args.dtype != "float32"),
                           rescale_grad=1.0 / (B * S))
    ts = TrainStep(sym, opt, data_shapes={"data": (B, S)},
                   label_shapes={"softmax_label": (B * S,)})
    ts.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                  magnitude=2))

    rng = np.random.RandomState(0)
    batches = []
    for _ in range(2):
        tok = jnp.asarray(rng.randint(0, args.lm_vocab, (B, S))
                          .astype(np.float32))
        lab = jnp.asarray(rng.randint(0, args.lm_vocab, (B * S,))
                          .astype(np.float32))
        batches.append({"data": tok, "softmax_label": lab})
    jax.block_until_ready(batches)
    probe = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        (ts.params, ts.states, ts.auxs, batches[0],
         jnp.float32(0.01), jnp.uint32(0)))

    dt, lat = _fori_timed(ts, batches, args.iters, lr=0.01,
                          warmup=args.warmup)
    flops_measured = _cost_flops(ts, probe, site="bench_transformer")
    if flops_measured:
        # XLA reports 0 FLOPs for custom calls: when the Pallas flash-
        # attention kernel is active its matmuls are counted analytically
        flops_measured += _flash_attention_flops(args)
    # hand numerator: the classic 6 * params * tokens training estimate
    # — it DROPS the attention matmuls entirely (the known bug), which
    # is exactly what the >10% mfu-vs-mfu_measured warning surfaces
    n_params = sum(int(np.prod(p.shape)) for p in ts.params.values())
    flops_hand = 6.0 * n_params * B * S

    tok_per_sec = B * S * args.iters / dt
    dev = jax.devices()[0]
    return {
        "metric": "transformer_lm_tokens_per_sec",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s",
        "device_kind": dev.device_kind,
        "config": "L%d d%d h%d S%d B%d vocab%d" % (
            args.lm_layers, args.lm_d_model, args.lm_heads, S, B,
            args.lm_vocab),
        **_mfu_fields(flops_hand, flops_measured, args.iters, dt,
                      dev.device_kind),
        **_latency_fields(lat["hist"], lat["compile_ms"]),
    }


def bench_transformer_mp(args):
    """Tensor-parallel transformer fit on the 2-D dp×mp GSPMD mesh
    (mx.sharding, docs/SHARDING.md): the model-parallelism acceptance
    arm. Two arms of the SAME fused Module fit step on the SAME
    TP-annotated symbol — ``replicated`` (mesh cleared, so the
    ``__sharding__`` annotations stay latent and the step runs
    dp-only) and ``mp`` (dp×mp=2 mesh: Megatron column/row-parallel
    FFN + head-sharded attention partitioned INSIDE the one donated
    program). Hard gates (SystemExit): the mp arm must stay
    single-launch (``train_dispatches_per_step == 1.0``), retrace-free
    in steady state, and its per-device param bytes must be ≤ 60% of
    the replicated arm's — the matmul shards must actually halve, not
    silently replicate."""
    import os
    import sys
    if "jax" not in sys.modules \
            and os.environ.get("JAX_PLATFORMS") == "cpu":
        # standalone --mode transformer on the CPU container: force 8
        # virtual devices so the dp4×mp2 mesh exists (same knob
        # tests/conftest.py pins for tier-1)
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import executor as _executor
    from mxnet_tpu import metric as metric_mod
    from mxnet_tpu import profiler, telemetry
    from mxnet_tpu.models import transformer
    from mxnet_tpu.module import fused_fit as _ff

    n_dev = len(jax.devices())
    if n_dev < 2 or n_dev % 2:
        return {"metric": "transformer_mp_dispatches_per_step",
                "value": None, "unit": "launches/step",
                "note": "%d visible device(s): the dp×mp=2 mesh needs "
                        "an even count >= 2" % n_dev}
    mp = 2
    dp = n_dev // mp
    B, S, V = 2 * dp, 32, 256
    steps = max(4, args.fit_steps)
    rng = np.random.RandomState(0)
    batches = [mx.io.DataBatch(
        data=[mx.nd.array(rng.randint(0, V, (B, S)).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, V, (B * S,))
                           .astype(np.float32))])
        for _ in range(steps + 2)]

    def run_arm(mesh_axes):
        mx.sharding.set_mesh(mesh_axes)
        try:
            sym = transformer.get_symbol(
                num_classes=V, num_layers=2, d_model=64, num_heads=4,
                seq_len=S, tensor_parallel="mp")
            mod = mx.Module(sym, context=[mx.tpu(i)
                                          for i in range(n_dev)])
            mod.bind(data_shapes=[("data", (B, S))],
                     label_shapes=[("softmax_label", (B * S,))])
            mod.init_params(mx.init.Xavier(rnd_type="gaussian",
                                           factor_type="in",
                                           magnitude=2))
            mod.init_optimizer(
                kvstore=mx.kv.create("device"), optimizer="sgd",
                optimizer_params={"learning_rate": 0.05,
                                  "momentum": 0.9})
            m = metric_mod.create("ce")
            t_c = time.perf_counter()
            mod.fit_step(batches[0], m)
            mod._fit_sync()
            compile_ms = (time.perf_counter() - t_c) * 1e3
            mod.fit_step(batches[1], m)     # steady-state entry
            mod._fit_sync()
            d0 = profiler.DEVICE_DISPATCHES.value
            h0 = metric_mod.HOST_SYNCS.value
            r0 = (_ff.FIT_RETRACES.value
                  + _executor.EXECUTOR_RETRACES.value)
            t0 = time.perf_counter()
            for b in batches[2:2 + steps]:
                mod.fit_step(b, m)
            mod._fit_sync()
            dt = time.perf_counter() - t0
            exe = mod._exec_group._exec
            params = [exe.arg_dict[n]
                      for n in mod._exec_group.param_names
                      if n in exe.arg_dict]
            snap = telemetry.memory_snapshot()
            return {
                "dispatches_per_step": round(
                    (profiler.DEVICE_DISPATCHES.value - d0) / steps, 2),
                "host_syncs_per_step": round(
                    (metric_mod.HOST_SYNCS.value - h0) / steps, 2),
                "steady_retraces": int(
                    _ff.FIT_RETRACES.value
                    + _executor.EXECUTOR_RETRACES.value - r0),
                "step_ms": round(dt / steps * 1000, 1),
                "compile_ms": _round_opt(compile_ms, 1),
                "param_bytes_per_device":
                    mx.sharding.per_device_param_bytes(params),
                "census_param_bytes_per_device":
                    snap["param_bytes_per_device"],
            }
        finally:
            mx.sharding.set_mesh(None)

    rep = run_arm(None)
    sharded = run_arm({"dp": dp, "mp": mp})
    sites = int(mx.sharding.CONSTRAINT_SITES.value)
    if sharded["dispatches_per_step"] != 1.0:
        raise SystemExit(
            "bench: transformer mp arm train_dispatches_per_step = %s "
            "(want 1.0) — model parallelism must stay inside the ONE "
            "donated program" % sharded["dispatches_per_step"])
    if sharded["steady_retraces"]:
        raise SystemExit(
            "bench: transformer mp arm retraced %d time(s) in steady "
            "state — mesh-fingerprint compile-cache regression"
            % sharded["steady_retraces"])
    ratio = sharded["param_bytes_per_device"] / max(
        1, rep["param_bytes_per_device"])
    if ratio > 0.60:
        raise SystemExit(
            "bench: mp arm per-device param bytes %d = %.0f%% of "
            "replicated %d (want <= 60%%) — the mp shards silently "
            "replicated" % (sharded["param_bytes_per_device"],
                            100 * ratio, rep["param_bytes_per_device"]))
    dev = jax.devices()[0]
    return {
        "metric": "transformer_mp_dispatches_per_step",
        "value": sharded["dispatches_per_step"],
        "unit": "launches/step",
        "device_kind": dev.device_kind,
        "config": "L2 d64 h4 S%d B%d vocab%d mesh=dp%dxmp%d" % (
            S, B, V, dp, mp),
        "transformer_mp": {"replicated": rep, "mp": sharded},
        "param_bytes_per_device": sharded["param_bytes_per_device"],
        "param_bytes_ratio_vs_replicated": round(ratio, 3),
        "sharding_constraint_sites": sites,
    }


def bench_quantized_inference(args):
    """Calibrated 8-bit ResNet-50 inference (VERDICT r3 item 5): the
    conv/FC stack runs int8(/uint8)×int8 with int32 accumulation
    (ops/quantization_ops.py), ranges pre-calibrated so no online max
    pass remains. Accuracy-delta vs fp32 is pinned by
    tests/test_quantization.py (agreement >= 99% on the trained
    fixture); this measures throughput on the chip."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.executor import _build_graph_fn
    from mxnet_tpu.contrib.quantization import quantize_symbol

    rng = np.random.RandomState(0)
    dev = jax.devices()[0]
    table = {}
    for qdtype in ("int8", "auto"):
        for batch in (32, 128):
            image_shape = (3, 224, 224)
            sym = models.get_symbol("resnet", num_classes=1000,
                                    image_shape=image_shape,
                                    dtype="float32")
            dshape = (batch,) + image_shape
            input_shapes = {"data": dshape, "softmax_label": (batch,)}
            arg_shapes, arg_types, aux_shapes, aux_types = \
                sym.infer_shape_type(input_shapes)
            arg_names = sym.list_arguments()
            shape_of = dict(zip(arg_names, arg_shapes))
            params = {}
            key = jax.random.key(0)
            for name, shp, dt in zip(arg_names, arg_shapes, arg_types):
                if name in input_shapes:
                    continue
                key, sub = jax.random.split(key)
                params[name] = (jax.random.normal(sub, shp, jnp.float32)
                                * 0.05).astype(dt)
            auxs = {}
            for name, shp, dt in zip(sym.list_auxiliary_states(),
                                     aux_shapes, aux_types):
                auxs[name] = (jnp.zeros(shp, dt) if name.endswith("_mean")
                              else jnp.ones(shp, dt))
            # pre-calibrated ranges for every conv/FC -> no online max
            calib = {n.name: (-4.0, 4.0) for n in sym._topo()
                     if not n.is_var
                     and n.op.name in ("Convolution", "FullyConnected")}
            offline = [n for n in arg_names
                       if n.endswith("_weight") and ("conv" in n
                                                     or "fc" in n
                                                     or "sc" in n)]
            qsym = quantize_symbol(
                sym, offline_params=offline, calib_ranges=calib,
                param_shapes={n: shape_of[n] for n in arg_names
                              if n not in input_shapes},
                quantized_dtype=qdtype)
            for name in offline:
                w = params.pop(name)
                lo = float(jnp.min(w))
                hi = float(jnp.max(w))
                from mxnet_tpu import nd as _nd
                qw, qlo, qhi = _nd.quantize(
                    _nd.NDArray(w), _nd.array(np.float32(lo)),
                    _nd.array(np.float32(hi)), out_type="int8")
                params[name + "_quantize"] = qw._data
                params[name + "_quantize_min"] = qlo._data
                params[name + "_quantize_max"] = qhi._data
            graph_fn = _build_graph_fn(qsym)

            def make_loop(n_iters):
                @jax.jit
                def fwd_loop(params, auxs, data):
                    def body(_, carry):
                        d, acc = carry
                        outs, _ = graph_fn(
                            {**params, "data": d,
                             "softmax_label": jnp.zeros((dshape[0],),
                                                        jnp.float32)},
                            auxs, np.uint32(0), False)
                        s = outs[0].sum()
                        patch = (s * 1e-30).astype(d.dtype).reshape(
                            (1,) * d.ndim)
                        d = jax.lax.dynamic_update_slice(
                            d, patch, (0,) * d.ndim)
                        return (d, acc + s)
                    _, acc = jax.lax.fori_loop(
                        0, n_iters, body, (data, jnp.float32(0)))
                    return acc
                return fwd_loop

            data = jnp.asarray(rng.uniform(-1, 1, dshape)
                               .astype(np.float32))
            n0 = 2
            short = make_loop(n0)
            long_ = make_loop(n0 + args.iters)
            float(short(params, auxs, data))
            float(long_(params, auxs, data))

            def timed(fn):
                t0 = time.perf_counter()
                float(fn(params, auxs, data))
                return time.perf_counter() - t0

            t_short = min(timed(short) for _ in range(2))
            t_long = min(timed(long_) for _ in range(2))
            dt_s = max(t_long - t_short, 1e-9)
            table["resnet50-%s-b%d" % (qdtype, batch)] = round(
                batch * args.iters / dt_s, 1)
    return {"metric": "quantized_inference_img_per_sec",
            "value": table.get("resnet50-int8-b128"),
            "unit": "img/s", "device_kind": dev.device_kind,
            "table": table}


def bench_inference(args):
    """Inference scoring (reference example/image-classification/
    benchmark_score.py + BASELINE.md inference tables): forward-only
    throughput per model at the reference's batch sizes. Weights are
    device-resident, data stays bound (the reference scores the same
    way: random fixed batch).

    Measurement: N forwards run CHAINED inside one ``lax.fori_loop``
    program (each iteration writes a tiny output-dependent patch into
    the data so XLA cannot hoist the loop-invariant forward), and the
    per-step time is the DIFFERENCE between an (n0+iters)-step and an
    n0-step program — cancelling launch/transfer round-trip overhead,
    which on a tunneled dev harness (~100ms RTT) would otherwise
    swamp millisecond-scale forwards. Independent async launches are
    not timeable here: the tunnel client coalesces identical
    dispatches (docs/PERF.md)."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.executor import _build_graph_fn

    configs = [
        ("resnet", {"num_layers": 50, "layout": args.layout}, 32),
        ("resnet", {"num_layers": 50, "layout": args.layout}, 128),
        ("resnet", {"num_layers": 152, "layout": args.layout}, 32),
        ("inception-bn", {}, 32),
        ("vgg", {"num_layers": 16}, 32),
        ("alexnet", {}, 32),
    ]
    rng = np.random.RandomState(0)
    table = {}
    dev = jax.devices()[0]
    for net, kw, batch in configs:
        image_shape = (3, 224, 224)
        sym = models.get_symbol(net, num_classes=1000,
                                image_shape=image_shape, dtype=args.dtype,
                                **kw)
        c, h, w = image_shape
        chlast = kw.get("layout") == "NHWC"
        dshape = (batch, h, w, c) if chlast else (batch,) + image_shape
        graph_fn = _build_graph_fn(sym)

        def make_loop(n_iters):
            @jax.jit
            def fwd_loop(params, auxs, data):
                def body(_, carry):
                    d, acc = carry
                    outs, _ = graph_fn(
                        {**params, "data": d,
                         "softmax_label": jnp.zeros((dshape[0],),
                                                    jnp.float32)},
                        auxs, np.uint32(0), False)
                    s = outs[0].sum()
                    patch = (s * 1e-30).astype(d.dtype).reshape(
                        (1,) * d.ndim)
                    d = jax.lax.dynamic_update_slice(
                        d, patch, (0,) * d.ndim)
                    return (d, acc + s)
                _, acc = jax.lax.fori_loop(
                    0, n_iters, body, (data, jnp.float32(0)))
                return acc
            return fwd_loop

        input_names = {"data", "softmax_label"}
        arg_shapes, arg_types, aux_shapes, aux_types = sym.infer_shape_type(
            {"data": dshape, "softmax_label": (batch,)},
            {"data": args.dtype} if args.dtype != "float32" else {})
        key = jax.random.key(0)
        params = {}
        for name, shp, dt in zip(sym.list_arguments(), arg_shapes,
                                 arg_types):
            if name in input_names:
                continue
            key, sub = jax.random.split(key)
            params[name] = (jax.random.normal(sub, shp, jnp.float32) * 0.05
                            ).astype(dt)
        auxs = {}
        for name, shp, dt in zip(sym.list_auxiliary_states(), aux_shapes,
                                 aux_types):
            auxs[name] = (jnp.zeros(shp, dt) if name.endswith("_mean")
                          else jnp.ones(shp, dt))
        data = jnp.asarray(rng.uniform(-1, 1, dshape).astype(np.float32)
                           ).astype(args.dtype)
        n0 = 2
        short = make_loop(n0)
        long_ = make_loop(n0 + args.iters)
        float(short(params, auxs, data))        # compile + warm
        float(long_(params, auxs, data))

        def timed(fn):
            t0 = time.perf_counter()
            float(fn(params, auxs, data))       # one launch, one readback
            return time.perf_counter() - t0

        t_short = min(timed(short) for _ in range(2))
        t_long = min(timed(long_) for _ in range(2))
        dt_s = max(t_long - t_short, 1e-9)
        label = "%s%s-b%d" % (net, kw.get("num_layers", ""), batch)
        table[label] = round(batch * args.iters / dt_s, 1)
    return {"metric": "inference_img_per_sec",
            "value": table.get("resnet50-b32"),
            "unit": "img/s", "device_kind": dev.device_kind,
            "dtype": args.dtype, "table": table,
            "vs_baseline_v100_fp32": round(
                table.get("resnet50-b32", 0) / 1076.81, 3)}


def bench_kvstore(args):
    """kvstore push/pull throughput on a ResNet-50-sized key set (the real
    param shapes from models.get_symbol, ``--kv-ndev`` simulated device
    gradient streams per key). Four arms: {eager per-key, compiled
    bucketed} x {dense f32, 2-bit compressed}. The headline
    ``kvstore_push_pull_gbps`` is bytes moved through push+pull per
    second on the bucketed dense path; ``speedup_vs_eager`` /
    ``speedup_vs_eager_2bit`` are the acceptance metrics (target >= 3x).

    What the bucketed path eliminates is per-key *dispatch*: the eager
    loop launches ~(2*ndev+1) device computations per key per step where
    the bucketed path launches one per bucket (``dispatches_per_step``
    in the output is the hardware-independent witness). On the tunneled
    TPU harness (docs/PERF.md: ~100ms per launch round-trip) that is the
    entire step time; on a 1-core CPU smoke run both arms sit at the
    memory-bandwidth floor and the ratio compresses toward 1x — read the
    dispatch counts, not the CPU ratio. Timing uses min-of-blocks to damp
    scheduler noise, with a readback liveness probe per arm."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models, nd
    from mxnet_tpu import kvstore_fused

    sym = models.get_symbol("resnet", num_classes=1000, num_layers=50,
                            image_shape=(3, 224, 224), dtype="float32")
    arg_shapes, _, _ = sym.infer_shape(data=(1, 3, 224, 224),
                                       softmax_label=(1,))
    keys, shapes = [], []
    for n, s in zip(sym.list_arguments(), arg_shapes):
        if n not in ("data", "softmax_label"):
            keys.append(n)
            shapes.append(s)
    total_bytes = sum(int(np.prod(s)) * 4 for s in shapes)
    ndev = args.kv_ndev
    rng = np.random.RandomState(0)
    weights_np = [rng.normal(0, 0.05, s).astype(np.float32) for s in shapes]
    grads_np = [[rng.normal(0, 0.01, s).astype(np.float32)
                 for _ in range(ndev)] for s in shapes]
    prios = [-i for i in range(len(keys))]
    blocks = max(2, args.iters // 4)

    def run(bucketed, compress, want_latency=False):
        kv = mx.kv.create("device")
        kv.set_bucketing(bucketed)
        if compress:
            kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=0.05, momentum=0.9, wd=1e-4,
            rescale_grad=1.0 / args.batch))
        grads = [[nd.array(g) for g in gl] for gl in grads_np]
        outs = [nd.zeros(s) for s in shapes]
        for k, w in zip(keys, weights_np):
            kv.init(k, nd.array(w))

        def step():
            kv.push(keys, grads, priority=prios)
            kv.pull(keys, out=outs)

        def timed_block(n):
            t0 = time.perf_counter()
            for _ in range(n):
                step()
            jax.block_until_ready([o._data for o in outs])
            return (time.perf_counter() - t0) / n

        # first warm-up step traces + compiles every bucket program —
        # its wall time is the arm's compile_ms witness
        t0 = time.perf_counter()
        step()
        jax.block_until_ready([o._data for o in outs])
        compile_ms = (time.perf_counter() - t0) * 1e3
        for _ in range(max(1, args.warmup) - 1):
            step()
        jax.block_until_ready([o._data for o in outs])
        per_step = min(timed_block(blocks) for _ in range(3))
        # per-step latency distribution (headline arm only — the extra
        # block of steps is not free on a bandwidth-bound host): host
        # wall time of each push+pull pair with one block at the end
        # (dispatch-dominated on the tunnel, bandwidth-bound on CPU —
        # same caveat as the mean)
        hist = None
        if want_latency:
            hist = _step_hist()
            for _ in range(blocks):
                t_s = time.perf_counter()
                step()
                hist.observe((time.perf_counter() - t_s) * 1e3)
            jax.block_until_ready([o._data for o in outs])
        probe = float(outs[0].asnumpy().ravel()[0])
        if not np.isfinite(probe):
            raise SystemExit("bench: non-finite weights in kvstore loop")
        return per_step, kv, {"compile_ms": compile_ms, "hist": hist}

    eager_dt, _, _ = run(False, False)
    fused_dt, kv, lat = run(True, False, want_latency=True)
    eager2_dt, _, _ = run(False, True)
    fused2_dt, kvc, _ = run(True, True)
    # push (grad bytes in, per device stream) + pull (weight bytes out)
    step_bytes = total_bytes * (ndev + 1)
    gbps = lambda dt: step_bytes / dt / 1e9
    st = kv._engine.stats
    # streaming flush dispatches several chunks per step — buckets per
    # step is the total over the run divided by steps (pushes of the
    # full keyset)
    n_steps = st["keys"] // len(keys)
    buckets_per_step = round(st["buckets"] / max(n_steps, 1))
    # eager per key: ndev compressions (2bit arm) + (ndev-1) adds + 1
    # updater apply; bucketed: one program per bucket
    eager_disp = len(keys) * (ndev * 1 + (ndev - 1) + 1)
    dev = jax.devices()[0]
    mh = bench_kvstore_multihost(args) if args.kv_hosts > 1 else {
        "kvstore_hosts": 1, "crosshost_bytes_per_step": 0}
    return {
        "metric": "kvstore_push_pull_gbps",
        "value": round(gbps(fused_dt), 2),
        "unit": "GB/s",
        "device_kind": dev.device_kind,
        "num_keys": len(keys),
        "ndev": ndev,
        "param_bytes": total_bytes,
        "eager_gbps": round(gbps(eager_dt), 2),
        "compressed_gbps": round(gbps(fused2_dt), 2),
        "eager_compressed_gbps": round(gbps(eager2_dt), 2),
        "speedup_vs_eager": round(eager_dt / fused_dt, 2),
        "speedup_vs_eager_2bit": round(eager2_dt / fused2_dt, 2),
        # logical wire ratio (f32 -> 2-bit); nominal by construction —
        # the local store never materializes packed bytes
        "kvstore_compress_ratio": 32 / 2.0,
        "bucket_count": buckets_per_step,
        "mean_bucket_occupancy": round(st["keys"] / max(st["buckets"], 1), 2),
        "bigarray_bound_bytes": kvstore_fused.bucket_byte_cap(),
        "dispatches_per_step": {"eager_2bit": eager_disp,
                                "bucketed": buckets_per_step},
        **_latency_fields(lat["hist"], lat["compile_ms"]),
        **mh,
    }


def bench_kvstore_multihost(args):
    """Multi-host arm of ``--mode kvstore``: spawn a ``--kv-hosts``-
    process kvstore='tpu' world (tools/run_multihost.py env contract,
    CPU jax.distributed backend) pushing a bucketed 2-bit key set, and
    report what travels per step. CPU-container convention (CHANGES.md):
    the numbers that matter are the dispatch-count witnesses and
    ``crosshost_bytes_per_step`` — wall time on a 1-core host measures
    process contention, not the collective. On this backend the engine
    uses the host transport (2 launches + 1 coordination-service
    allgather per bucket); a real pod rides GSPMD at 1 launch.

    Runs the world TWICE — backward-overlapped (default) vs
    ``MXNET_KVSTORE_OVERLAP=0`` serial — under a bucket cap small
    enough that the streaming flush engages, and GATES the A/B
    (docs/KVSTORE.md "Overlapped push"): the overlapped arm must
    dispatch no more programs per step than serial (overlap reorders
    work, it never adds any) and its overlap witness must actually
    fire; either failure is a SystemExit, not a report field."""
    import os
    import subprocess
    import sys as _sys
    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)

    def arm(overlap):
        proc = subprocess.run(
            [_sys.executable,
             os.path.join(root, "tools", "run_multihost.py"),
             "-n", str(args.kv_hosts),
             # cap = the largest key (256 KiB): full buckets stream out
             # mid-push, the partial tail rides the sync point
             "--env", "MXNET_KVSTORE_BIGARRAY_BOUND=262144",
             "--env", "MXNET_KVSTORE_OVERLAP=%d" % overlap, "--",
             _sys.executable, os.path.join(root, "bench.py"),
             "--mode", "kvstore-mh-worker", "--iters", str(args.iters),
             "--batch", str(args.batch)],
            env=env, capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise SystemExit("bench: multi-host kvstore arm failed:\n%s"
                             % proc.stderr[-2000:])
        line = next(l for l in proc.stdout.splitlines()
                    if l.startswith("{") and "kvstore_hosts" in l)
        return json.loads(line)

    ov, ser = arm(1), arm(0)
    if ov["kvstore_overlap_dispatches_per_step"] <= 0:
        raise SystemExit(
            "bench: overlap witness never fired — no bucket collective "
            "was dispatched before the final backward bucket landed")
    if ser["kvstore_overlap_dispatches_per_step"] != 0:
        raise SystemExit("bench: MXNET_KVSTORE_OVERLAP=0 arm still "
                         "ticked the overlap witness")
    if ov["kvstore_mh_dispatches_per_step"] > \
            ser["kvstore_mh_dispatches_per_step"]:
        raise SystemExit(
            "bench: overlapped push dispatched MORE programs per step "
            "than serial (%.2f > %.2f) — overlap must reorder work, "
            "not add any" % (ov["kvstore_mh_dispatches_per_step"],
                             ser["kvstore_mh_dispatches_per_step"]))
    ov["kvstore_mh_serial_dispatches_per_step"] = \
        ser["kvstore_mh_dispatches_per_step"]
    return ov


def bench_kvstore_mh_worker(args):
    """One rank of the multi-host kvstore arm (spawned by
    bench_kvstore_multihost under the MXTPU_* env contract; also runs
    standalone as a single-process world). Rank 0 prints the JSON."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, profiler, telemetry

    kv = mx.kv.create("tpu")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05, momentum=0.9,
                                      wd=1e-4,
                                      rescale_grad=1.0 / args.batch))
    shapes = [(256, 256), (512, 128), (1000,), (64, 3, 3, 3), (256,)]
    keys = ["mh_p%d" % i for i in range(len(shapes))]
    rng = np.random.RandomState(0)          # same init on every rank
    for k, s in zip(keys, shapes):
        kv.init(k, nd.array(rng.normal(0, 0.05, s).astype(np.float32)))
    grng = np.random.RandomState(1 + kv.rank)   # per-rank gradients

    def step():
        kv.push(keys, [[nd.array(grng.normal(0, 0.01, s)
                                 .astype(np.float32))] for s in shapes])
    step()                                  # warmup: trace + compile
    kv._sync_engine()     # land the warmup's pipelined applies before
    steps = max(4, min(args.iters, 16))     # snapshotting the counters
    xb = telemetry.REGISTRY.get("kvstore_tpu_crosshost_bytes")
    wit = telemetry.REGISTRY.get("kvstore_overlap_dispatches")
    d0, x0, w0 = (profiler.DEVICE_DISPATCHES.value, xb.value,
                  wit.value)
    for _ in range(steps):
        step()
    kv._sync_engine()
    kv.barrier()
    if kv.rank == 0:
        print(json.dumps({
            "kvstore_hosts": kv.num_workers,
            "crosshost_bytes_per_step":
                int((xb.value - x0) / steps),
            "kvstore_mh_dispatches_per_step":
                round((profiler.DEVICE_DISPATCHES.value - d0) / steps, 2),
            "kvstore_overlap_dispatches_per_step":
                round((wit.value - w0) / steps, 2),
            "kvstore_mh_transport":
                "gspmd" if kv._gspmd_ok else "host",
            "kvstore_mh_keys": len(keys),
            "kvstore_mh_steps": steps,
        }))


def bench_dlrm_partition(args):
    """Multi-host arm of ``--mode dlrm``: spawn a ``--dlrm-hosts``-
    process kvstore='tpu' world where the stacked table row-partitions
    ACROSS hosts (docs/EMBEDDING.md "Multi-host partitioning") and GATE
    the pod-partitioning acceptance criteria: resident table bytes per
    host must scale as 1/W and the cross-host row_sparse apply must
    stay at ONE sparse dispatch per step (the replicated host transport
    needs two). Either failure is a SystemExit, not a report field."""
    import os
    import subprocess
    import sys as _sys
    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [_sys.executable, os.path.join(root, "tools", "run_multihost.py"),
         "-n", str(args.dlrm_hosts), "--",
         _sys.executable, os.path.join(root, "bench.py"),
         "--mode", "dlrm-part-worker", "--iters", str(args.iters)],
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise SystemExit("bench: multi-host dlrm arm failed:\n%s"
                         % proc.stderr[-2000:])
    line = next(l for l in proc.stdout.splitlines()
                if l.startswith("{") and "dlrm_hosts" in l)
    out = json.loads(line)
    W = out["dlrm_hosts"]
    if not out["dlrm_partitioned"]:
        raise SystemExit("bench: table did not partition in a %d-host "
                         "world" % W)
    if out["table_bytes_per_host_ratio"] > 1.0 / W + 1e-6:
        raise SystemExit(
            "bench: table_bytes_per_host_ratio %.3f > 1/%d — the slab "
            "did not replace the replicated table"
            % (out["table_bytes_per_host_ratio"], W))
    if out["crosshost_sparse_dispatches_per_step"] != 1:
        raise SystemExit(
            "bench: partitioned sparse apply took %.2f dispatches/step "
            "(want exactly 1 — the single cross-host launch)"
            % out["crosshost_sparse_dispatches_per_step"])
    return out


def bench_dlrm_part_worker(args):
    """One rank of the pod-partitioned DLRM arm (spawned by
    bench_dlrm_partition under the MXTPU_* env contract). Rank 0
    prints the JSON line the parent parses and gates on."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, telemetry
    from mxnet_tpu.embedding import ShardedEmbedding
    from mxnet_tpu.embedding.engine import SPARSE_DISPATCHES
    from mxnet_tpu.embedding.lookup import LOOKUPS

    V, D, F, B = 64, 8, 4, 8
    kv = mx.kv.create("tpu")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05,
                                      lazy_update=True,
                                      rescale_grad=1.0 / B))
    blk = ShardedEmbedding(F * V, D)
    blk.initialize()
    tbl = telemetry.REGISTRY.get("embedding_table_bytes_per_host")
    a2a = telemetry.REGISTRY.get("embedding_alltoall_bytes")
    key = blk.attach_to_kvstore(kv)
    part = kv._partitioned.get(key)
    rng = np.random.RandomState(11 + kv.rank)   # per-rank index stream
    offs = (np.arange(F) * V)[None, :]

    def step():
        idx = np.minimum(rng.zipf(1.2, size=(B, F)) - 1, V - 1) + offs
        with autograd.record():
            out = blk(nd.array(idx))
        out._grad = nd.array(rng.normal(0, 1, out.shape)
                             .astype(np.float32))
        blk.sparse_push(kv, key=key)

    step()                                  # warmup: trace + compile
    steps = max(4, min(args.iters, 12))
    s0, l0, a0 = SPARSE_DISPATCHES.value, LOOKUPS.value, a2a.value
    for _ in range(steps):
        step()
    kv.barrier()
    if kv.rank == 0:
        print(json.dumps({
            "dlrm_hosts": kv.num_workers,
            "dlrm_partitioned": part is not None,
            "table_bytes_per_host_ratio":
                round(tbl.value / (F * V * D * 4), 3),
            "crosshost_sparse_dispatches_per_step":
                round((SPARSE_DISPATCHES.value - s0) / steps, 2),
            "crosshost_lookup_dispatches_per_step":
                round((LOOKUPS.value - l0) / steps, 2),
            "embedding_alltoall_bytes_per_step":
                int((a2a.value - a0) / steps),
        }))


def bench_dlrm(args):
    """Recommendation-scale training (mx.embedding, docs/EMBEDDING.md):
    an embedding-dominated DLRM-style step — F categorical features
    share one stacked (F*V, D) ``ShardedEmbedding`` table via
    per-feature index offsets, indices drawn zipf(1.2) so traffic is
    heavy-tailed (a few hot rows, a long cold tail, ragged unique-row
    counts every step — the retrace stressor). Each step is ONE compiled
    lookup dispatch (B*F is power-of-two by construction, so no unpad
    slice) plus ONE compiled sparse-apply dispatch through ``kv.push``;
    ``sparse_dispatches_per_step <= 2`` and zero steady-state retraces
    across the ragged batches are asserted, not just reported. The
    parity arm replays the identical gradient stream through the EAGER
    row_sparse path (bucketing off) and compares final tables at
    rtol 2e-5 — the compiled pipeline must train the same model."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.embedding import ShardedEmbedding
    from mxnet_tpu.embedding.lookup import LOOKUPS, LOOKUP_RETRACES
    from mxnet_tpu.embedding.engine import (SPARSE_DISPATCHES,
                                            SPARSE_RETRACES)
    from mxnet_tpu import telemetry, profiler

    V, D, F, B = (args.dlrm_vocab, args.dlrm_dim,
                  args.dlrm_features, args.dlrm_batch)
    if (B * F) & (B * F - 1):
        raise SystemExit("bench: --dlrm-batch * --dlrm-features must be "
                         "a power of two (single-dispatch lookup)")
    rng = np.random.RandomState(7)
    steps = max(4, args.iters)
    # per-step (B, F) zipf indices, offset feature f into its own V rows
    offs = (np.arange(F) * V)[None, :]
    batches = [np.minimum(rng.zipf(1.2, size=(B, F)) - 1, V - 1) + offs
               for _ in range(args.warmup + steps)]
    upstream = [rng.normal(0, 1, (B, F, D)).astype(np.float32)
                for _ in range(args.warmup + steps)]
    w0 = rng.normal(0, 0.05, (F * V, D)).astype(np.float32)

    def run(bucketed):
        blk = ShardedEmbedding(F * V, D)
        blk.initialize()
        kv = mx.kv.create("local")
        kv.set_bucketing(bucketed)
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05,
                                          lazy_update=True,
                                          rescale_grad=1.0 / B))
        blk.attach_to_kvstore(kv)
        key = "embedding:%s" % blk.weight.name
        # both arms start from the same table
        kv._store[key]._set_data(jax.numpy.asarray(w0))

        def step(i):
            with autograd.record():
                out = blk(nd.array(batches[i]))
                # stand-in for the dense interaction tower: a weighted
                # sum whose gradient w.r.t. the lookup is upstream[i]
                loss = (out * nd.array(upstream[i])).sum()
            loss.backward()
            blk.sparse_push(kv, key=key)
        return blk, kv, key, step

    # -- compiled arm ---------------------------------------------------
    blk, kv, key, step = run(bucketed=True)
    t0 = time.perf_counter()
    step(0)
    jax.block_until_ready(kv._store[key]._data)
    compile_ms = (time.perf_counter() - t0) * 1e3
    for i in range(1, args.warmup):
        step(i)
    jax.block_until_ready(kv._store[key]._data)
    l0, s0 = LOOKUPS.value, SPARSE_DISPATCHES.value
    lr0, sr0 = LOOKUP_RETRACES.value, SPARSE_RETRACES.value
    hist = _step_hist()
    t0 = time.perf_counter()
    for i in range(steps):
        t_s = time.perf_counter()
        step(args.warmup + i)
        hist.observe((time.perf_counter() - t_s) * 1e3)
    jax.block_until_ready(kv._store[key]._data)
    dt = time.perf_counter() - t0
    retraces = (LOOKUP_RETRACES.value - lr0) + (SPARSE_RETRACES.value - sr0)
    sparse_per_step = (SPARSE_DISPATCHES.value - s0) / steps
    lookup_per_step = (LOOKUPS.value - l0) / steps
    if retraces:
        raise SystemExit("bench: %d embedding retraces across ragged "
                         "measured steps — the runtime/static split "
                         "leaked a shape into a trace" % retraces)
    if sparse_per_step > 2:
        raise SystemExit("bench: %.1f sparse dispatches/step > 2" %
                         sparse_per_step)
    compiled_w = np.asarray(kv._store[key]._data)

    # -- parity arm: identical stream through the EAGER rsp path --------
    _, kv_e, key_e, step_e = run(bucketed=False)
    for i in range(args.warmup + steps):
        step_e(i)
    eager_w = np.asarray(kv_e._store[key_e]._data)
    err = np.abs(compiled_w - eager_w).max() / max(
        np.abs(eager_w).max(), 1e-12)
    if err > 2e-5:
        raise SystemExit("bench: compiled-vs-eager sparse training "
                         "diverged (rel err %.2e > 2e-5)" % err)

    hbm = telemetry.REGISTRY.get("embedding_hbm_bytes")
    dev = jax.devices()[0]
    mh = bench_dlrm_partition(args) if args.dlrm_hosts > 1 else {
        "dlrm_hosts": 1, "table_bytes_per_host_ratio": 1.0,
        "crosshost_sparse_dispatches_per_step": 0}
    return {
        "metric": "dlrm_lookups_per_sec",
        "value": round(B * F * steps / dt, 1),
        "unit": "lookups/s",
        "device_kind": dev.device_kind,
        "dlrm_table_rows": F * V,
        "dlrm_dim": D,
        "dlrm_features": F,
        "dlrm_batch": B,
        "dlrm_steps": steps,
        "dlrm_lookups_per_sec": round(B * F * steps / dt, 1),
        "lookup_dispatches_per_step": round(lookup_per_step, 2),
        "sparse_dispatches_per_step": round(sparse_per_step, 2),
        "embedding_retraces": retraces,
        "embedding_hbm_bytes": int(hbm.value),
        "dlrm_parity_rel_err": float(err),
        **_latency_fields(hist, compile_ms),
        **mh,
    }


def bench_fit(args):
    """Module-fit step witnesses: the single-launch fused fit step
    (module/fused_fit.py) vs the eager fwd_bwd + bucketed-kvstore pair
    on a ResNet-50 fit configuration (SGD momentum + wd, device
    kvstore, Accuracy metric — the Module path's default shape), plus
    two fused-optimizer acceptance arms: f32 Adam and bf16
    multi-precision Adam (f32 masters + dynamic loss scaler inside the
    donated program; docs/TRAINING.md "Mixed precision"). Both must
    hold train_dispatches_per_step == 1, and the bf16 fit program must
    report fewer bytes_accessed than the f32 one — gated only on
    backends with native bf16 compute (XLA CPU emulates bf16 in f32
    and reports the opposite; the JSON carries a note instead).

    The headline numbers are hardware-independent launch/sync counters,
    not wall clock: ``train_dispatches_per_step`` (profiler
    DEVICE_DISPATCHES delta per step — fused target ≤ 2, eager ~32) and
    ``host_syncs_per_step`` (metric-layer blocking readbacks — fused
    target 0 between Speedometer/epoch boundaries). On the 1-core CPU
    container both arms sit at the memory-bandwidth floor so step_ms
    compresses toward 1x; on the tunneled TPU harness each dispatch
    costs ~100 ms RTT (docs/PERF.md) and the launch count IS the step
    time."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models, nd
    from mxnet_tpu import metric as metric_mod
    from mxnet_tpu import profiler

    from mxnet_tpu import telemetry

    image_shape = tuple(int(x) for x in args.fit_image_shape.split(","))
    batch = args.fit_batch
    steps = args.fit_steps
    syms = {dt: models.get_symbol("resnet", num_classes=1000,
                                  num_layers=args.num_layers,
                                  image_shape=image_shape, dtype=dt)
            for dt in ("float32", "bfloat16")}
    rng = np.random.RandomState(0)
    c, h, w = image_shape
    X = rng.uniform(-1, 1, (batch, c, h, w)).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.float32)

    # arm -> (fused?, optimizer, optimizer_params, train dtype).  The
    # adam and bf16+MP arms are the PR's acceptance witnesses: strict
    # train_dispatches_per_step == 1, and the bf16 program must touch
    # fewer bytes than the f32 one (telemetry.programs cost analysis).
    sgd_params = {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}
    adam_params = {"learning_rate": 1e-3, "wd": 1e-4}
    # "fused" runs with the in-launch numerics sentinels ON (the
    # default); "fused_nosent" is the identical config with
    # MXNET_SENTINEL_NUMERICS=0 — the pair yields sentinel_overhead_pct
    # and the hard gate that the witnesses add ZERO dispatches/syncs
    arm_cfg = {
        "eager": (False, "sgd", sgd_params, "float32", True),
        "fused": (True, "sgd", sgd_params, "float32", True),
        "fused_nosent": (True, "sgd", sgd_params, "float32", False),
        "fused_adam": (True, "adam", adam_params, "float32", True),
        "fused_bf16": (True, "adam",
                       dict(adam_params, multi_precision=True),
                       "bfloat16", True),
    }

    arms = {}
    for arm, (fused, opt, opt_params, train_dtype,
              sentinels) in arm_cfg.items():
        prev_sent = os.environ.get("MXNET_SENTINEL_NUMERICS")
        os.environ["MXNET_SENTINEL_NUMERICS"] = "1" if sentinels else "0"
        n_programs = len(telemetry.programs(analyze=False))
        mod = mx.Module(syms[train_dtype])
        mod._fused_fit_enabled = fused
        mod.bind(data_shapes=[("data", X.shape)],
                 label_shapes=[("softmax_label", (batch,))])
        mod.init_params(mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2))
        mod.init_optimizer(kvstore=mx.kv.create("device"), optimizer=opt,
                           optimizer_params=dict(opt_params))
        m = metric_mod.Accuracy()
        batch_nd = mx.io.DataBatch(data=[nd.array(X)], label=[nd.array(y)])

        def one_step():
            mod.fit_step(batch_nd, m)
            mod.update_metric(m, batch_nd.label)

        def block():
            mod._fit_sync()     # waits on a trainable param (step output)

        t_c = time.perf_counter()
        one_step()                       # compile + warm
        block()
        compile_ms = (time.perf_counter() - t_c) * 1e3
        d0 = profiler.DEVICE_DISPATCHES.value
        h0 = metric_mod.HOST_SYNCS.value
        hist = _step_hist()
        t0 = time.perf_counter()
        for _ in range(steps):
            t_s = time.perf_counter()
            one_step()
            hist.observe((time.perf_counter() - t_s) * 1e3)
        block()
        dt = time.perf_counter() - t0
        # capture the loop deltas BEFORE the boundary get() below — that
        # readback is the scheduled Speedometer-style sync, not a
        # per-batch one
        d_steps = profiler.DEVICE_DISPATCHES.value - d0
        h_steps = metric_mod.HOST_SYNCS.value - h0
        _name, val = m.get()             # boundary readback (liveness)
        if not np.isfinite(val):
            raise SystemExit("bench: non-finite fit metric (%s arm)" % arm)
        arms[arm] = {
            "dispatches_per_step": round(d_steps / steps, 2),
            "host_syncs_per_step": round(h_steps / steps, 2),
            "step_ms": round(dt / steps * 1000, 1),
            "train_dtype": train_dtype,
            "fused_optimizer": (type(mod._optimizer).__name__
                                if fused and mod._fused_fit is not None
                                else None),
            **_latency_fields(hist, compile_ms),
        }
        if fused and mod._fused_fit is None:
            raise SystemExit("bench: %s arm fell back to eager — "
                             "eligibility regression" % arm)
        # the fit program's compiler-reported cost (bytes moved is the
        # bf16 win on an HBM-bound model; flops feed mfu_measured)
        fit_rows = [r for r in telemetry.programs()[n_programs:]
                    if r["site"] == "fit_step"
                    and r.get("bytes_accessed")]
        arms[arm]["bytes_accessed"] = (
            max(r["bytes_accessed"] for r in fit_rows) if fit_rows
            else None)
        if arm == "fused_bf16":
            scaler = getattr(mod, "_loss_scaler", None)
            if scaler is not None:
                scaler.publish()
                arms[arm]["loss_scale_skips"] = scaler.skips
            else:
                arms[arm]["loss_scale_skips"] = None
        if prev_sent is None:
            os.environ.pop("MXNET_SENTINEL_NUMERICS", None)
        else:
            os.environ["MXNET_SENTINEL_NUMERICS"] = prev_sent
    # acceptance: the fused Adam arms are SINGLE-launch, f32 and bf16+MP
    for arm in ("fused_adam", "fused_bf16"):
        if arms[arm]["dispatches_per_step"] != 1:
            raise SystemExit(
                "bench: %s arm train_dispatches_per_step = %s (want 1)"
                % (arm, arms[arm]["dispatches_per_step"]))
    # acceptance: the in-launch sentinels ride the SAME program — with
    # them on the fused arm must stay single-launch and sync-free, and
    # the on/off dispatch counts must be IDENTICAL (the deterministic
    # overhead convention; wall clock is reported, not gated, because
    # the 1-core CPU container's p50 jitter exceeds any real delta)
    if arms["fused"]["dispatches_per_step"] != 1:
        raise SystemExit(
            "bench: sentinels-on fused arm train_dispatches_per_step = "
            "%s (want 1)" % arms["fused"]["dispatches_per_step"])
    if arms["fused"]["host_syncs_per_step"] != 0:
        raise SystemExit(
            "bench: sentinels-on fused arm host_syncs_per_step = %s "
            "(want 0)" % arms["fused"]["host_syncs_per_step"])
    if arms["fused"]["dispatches_per_step"] \
            != arms["fused_nosent"]["dispatches_per_step"]:
        raise SystemExit(
            "bench: sentinel witnesses changed the dispatch count "
            "(%s on vs %s off)"
            % (arms["fused"]["dispatches_per_step"],
               arms["fused_nosent"]["dispatches_per_step"]))
    p50_off = arms["fused_nosent"]["step_ms_p50"]
    sentinel_overhead_pct = (
        round((arms["fused"]["step_ms_p50"] - p50_off) / p50_off * 100, 2)
        if p50_off else None)
    from mxnet_tpu.telemetry import sentinel as _sentinel
    sentinel_alerts = int(
        _sentinel.SENTINEL_ALERTS.value
        + sum(c.value for c in _sentinel.SENTINEL_ALERTS.children()))
    dev = jax.devices()[0]
    # XLA CPU upcasts bf16 compute to f32 (a bf16 matmul *reports more*
    # bytes accessed than the f32 one), so the fewer-bytes acceptance
    # gate is meaningful only on backends with native low-precision
    # compute; on the CPU container the values are reported, not gated
    ba_f32 = arms["fused_adam"]["bytes_accessed"]
    ba_bf16 = arms["fused_bf16"]["bytes_accessed"]
    bytes_note = None
    if jax.default_backend() == "cpu":
        bytes_note = ("bytes_accessed gate skipped: XLA CPU emulates "
                      "bf16 in f32 (docs/TRAINING.md Mixed precision)")
    elif ba_f32 and ba_bf16 and not ba_bf16 < ba_f32:
        raise SystemExit(
            "bench: bf16 fit program moves %d bytes >= f32's %d — "
            "low-precision regression" % (ba_bf16, ba_f32))
    return {
        "metric": "train_dispatches_per_step",
        "value": arms["fused"]["dispatches_per_step"],
        "unit": "launches/step",
        "device_kind": dev.device_kind,
        "config": "resnet%d b%d %s sgd-mom+adam(f32/bf16-mp) kv=device "
                  "2bit=off" % (args.num_layers, batch,
                                args.fit_image_shape),
        "train_dispatches_per_step": {
            a: arms[a]["dispatches_per_step"] for a in arms},
        "host_syncs_per_step": {
            a: arms[a]["host_syncs_per_step"] for a in arms},
        "fit_step_ms": {a: arms[a]["step_ms"] for a in arms},
        "fused_optimizer": {a: arms[a]["fused_optimizer"] for a in arms},
        "train_dtype": {a: arms[a]["train_dtype"] for a in arms},
        "train_bytes_accessed": {a: arms[a]["bytes_accessed"]
                                 for a in arms},
        **({"train_bytes_note": bytes_note} if bytes_note else {}),
        "loss_scale_skips": arms["fused_bf16"]["loss_scale_skips"],
        "sentinel_overhead_pct": sentinel_overhead_pct,
        "sentinel_alerts": sentinel_alerts,
        "step_ms_p50": arms["fused"]["step_ms_p50"],
        "step_ms_p99": arms["fused"]["step_ms_p99"],
        "compile_ms": arms["fused"]["compile_ms"],
    }


def bench_checkpoint(args):
    """mx.checkpoint witnesses: async vs blocking save latency, bytes
    per checkpoint, and — the headline — the training-thread BLOCK time
    of an async save (``checkpoint_block_ms``: device→host snapshot +
    enqueue; serialization and IO run on the writer thread).

    Acceptance shape (docs/CHECKPOINT.md): ``checkpoint_block_ms`` p50
    stays under the fit-step p50 — checkpointing never costs a full
    step — and the fused-step / bucketed-kvstore retrace witnesses stay
    flat with checkpointing enabled. Measured on the bench_fit model
    (ResNet fit config, 2-bit compression ON so residual capture is
    priced in)."""
    import os
    import shutil
    import tempfile

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models, nd, telemetry
    from mxnet_tpu import checkpoint as ckpt

    image_shape = tuple(int(x) for x in args.fit_image_shape.split(","))
    batch = args.fit_batch
    sym = models.get_symbol("resnet", num_classes=1000,
                            num_layers=args.num_layers,
                            image_shape=image_shape, dtype="float32")
    rng = np.random.RandomState(0)
    c, h, w = image_shape
    X = rng.uniform(-1, 1, (batch, c, h, w)).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.float32)
    mod = mx.Module(sym, compression_params={"type": "2bit",
                                             "threshold": 0.5})
    mod.bind(data_shapes=[("data", X.shape)],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params(mx.init.Xavier(rnd_type="gaussian",
                                   factor_type="in", magnitude=2))
    mod.init_optimizer(kvstore=mx.kv.create("device"), optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9, "wd": 1e-4})
    batch_nd = mx.io.DataBatch(data=[nd.array(X)], label=[nd.array(y)])
    mod.fit_step(batch_nd)               # compile + warm
    mod._fit_sync()
    r_fit0 = telemetry.REGISTRY.get("fit_step_retraces").value
    r_kv0 = telemetry.REGISTRY.get("kvstore_bucket_retraces").value

    step_hist = _step_hist()
    for _ in range(args.fit_steps):
        t_s = time.perf_counter()
        mod.fit_step(batch_nd)
        step_hist.observe((time.perf_counter() - t_s) * 1e3)
    mod._fit_sync()

    tmp = tempfile.mkdtemp(prefix="mx-bench-ckpt-")
    n_saves = args.ckpt_saves
    save_hist = telemetry.REGISTRY.get("checkpoint_save_ms")
    bytes_ctr = telemetry.REGISTRY.get("checkpoint_bytes")
    try:
        mgr = ckpt.CheckpointManager(os.path.join(tmp, "ck"), module=mod,
                                     keep=2, install_preemption=False)
        # async arm: the training thread pays only the snapshot+enqueue
        block_ms, t_c = [], time.perf_counter()
        snap0, b0 = save_hist.snapshot(), bytes_ctr.value
        for i in range(n_saves):
            mod.fit_step(batch_nd)
            t0 = time.perf_counter()
            mgr.save(step=i + 1)
            block_ms.append((time.perf_counter() - t0) * 1e3)
        assert mgr.drain(600), "bench: checkpoint writer failed to drain"
        async_wall_ms = (time.perf_counter() - t_c) * 1e3
        async_save_p50 = telemetry.hist_quantile(
            save_hist.snapshot(), 0.5, since=snap0)
        per_save_bytes = (bytes_ctr.value - b0) // n_saves
        # blocking arm: serialize + write + fsync + rename inline
        sync_ms = []
        for i in range(n_saves):
            mod.fit_step(batch_nd)
            t0 = time.perf_counter()
            mgr.save(step=100 + i, block=True)
            sync_ms.append((time.perf_counter() - t0) * 1e3)
        mgr.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    block_ms.sort()
    sync_ms.sort()
    step_p50 = step_hist.quantile(0.5)
    block_p50 = block_ms[len(block_ms) // 2]
    retr_fit = telemetry.REGISTRY.get("fit_step_retraces").value - r_fit0
    retr_kv = telemetry.REGISTRY.get("kvstore_bucket_retraces").value \
        - r_kv0
    dev = jax.devices()[0]
    return {
        "metric": "checkpoint_block_ms",
        "value": _round_opt(block_p50),
        "unit": "ms",
        "device_kind": dev.device_kind,
        "config": "resnet%d b%d %s sgd-mom kv=device 2bit=on" % (
            args.num_layers, batch, args.fit_image_shape),
        "checkpoint_save_ms": {
            "async": _round_opt(async_save_p50),
            "blocking": _round_opt(sync_ms[len(sync_ms) // 2]),
        },
        "checkpoint_bytes": int(per_save_bytes),
        "checkpoint_async_wall_ms": _round_opt(async_wall_ms),
        "fit_step_ms_p50": _round_opt(step_p50),
        "block_lt_step_p50": bool(step_p50 is None
                                  or block_p50 < step_p50),
        "fit_step_retraces_delta": int(retr_fit),
        "kvstore_bucket_retraces_delta": int(retr_kv),
        "saves_per_arm": n_saves,
    }


def bench_serving(args):
    """mx.serving throughput: concurrent clients against the in-process
    ModelServer (dynamic micro-batching + bucket padding over a jitted
    ResNet forward). The headline is ``serving_qps`` — single-example
    requests served per second end to end (queue + batcher + device),
    NOT the raw batched-forward img/s, so it prices the batching control
    plane the way a traffic-serving deployment would see it."""
    import threading

    import jax
    import numpy as np
    from mxnet_tpu import models
    from mxnet_tpu.serving import ModelServer

    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    sym = models.get_symbol("resnet", num_classes=1000,
                            num_layers=args.num_layers,
                            image_shape=image_shape, dtype="float32")
    arg_shapes, _, aux_shapes = sym.infer_shape(
        data=(1,) + image_shape, softmax_label=(1,))
    rng = np.random.RandomState(0)
    params = {n: (rng.normal(0, 0.05, s).astype(np.float32))
              for n, s in zip(sym.list_arguments(), arg_shapes)
              if n not in ("data", "softmax_label")}
    auxs = {}
    for n, s in zip(sym.list_auxiliary_states(), aux_shapes):
        auxs[n] = (np.zeros(s, np.float32) if n.endswith("_mean")
                   else np.ones(s, np.float32))

    from mxnet_tpu import telemetry

    n_req = args.serving_requests
    # construction compiles every bucket on every replica (warmup=True):
    # its wall time is the serving arm's compile_ms witness
    t_c = time.perf_counter()
    srv = ModelServer(sym, params, auxs, {"data": image_shape},
                      num_replicas=args.serving_replicas,
                      max_batch_size=args.serving_max_batch,
                      max_latency_ms=args.serving_latency_ms,
                      queue_capacity=n_req + args.serving_max_batch)
    compile_ms = (time.perf_counter() - t_c) * 1e3
    telemetry.JIT_COMPILE_MS.observe(compile_ms)
    try:
        xs = [rng.uniform(-1, 1, image_shape).astype(np.float32)
              for _ in range(8)]
        # warmup already compiled every bucket; a short served burst
        # warms the control plane too — then zero the stats so the
        # occupancy-1 warmup batches don't bias the reported metrics
        for x in xs:
            srv.predict({"data": x})
        srv.drain(timeout=600)
        srv.reset_stats()
        # registry latency histogram: percentiles over THIS run come
        # from the delta against the post-warmup snapshot
        lat_hist = telemetry.REGISTRY.get("serving_request_ms")
        lat_snap0 = lat_hist.snapshot()

        futs = []
        lock = threading.Lock()
        t0 = time.perf_counter()

        def client(k):
            local = []
            for i in range(n_req // args.serving_clients):
                local.append(srv.submit({"data": xs[(k + i) % len(xs)]}))
            with lock:
                futs.extend(local)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(args.serving_clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for f in futs:
            f.result(timeout=600)
        dt = time.perf_counter() - t0
        st = srv.stats()
    finally:
        srv.stop()
    dev = jax.devices()[0]
    return {
        "metric": "serving_qps",
        "value": round(len(futs) / dt, 1),
        "unit": "req/s",
        "device_kind": dev.device_kind,
        "replicas": args.serving_replicas,
        "max_batch_size": args.serving_max_batch,
        "max_latency_ms": args.serving_latency_ms,
        "mean_batch_occupancy": round(st["batches"]["mean_occupancy"], 2)
        if st["batches"]["mean_occupancy"] else None,
        "latency_p50_ms": st["latency_ms"]["p50"],
        "latency_p99_ms": st["latency_ms"]["p99"],
        # serving's "step" is one request end to end: percentiles from
        # the serving_request_ms registry histogram, this run only
        "step_ms_p50": _round_opt(
            telemetry.hist_quantile(lat_hist.snapshot(), 0.5,
                                    since=lat_snap0)),
        "step_ms_p99": _round_opt(
            telemetry.hist_quantile(lat_hist.snapshot(), 0.99,
                                    since=lat_snap0)),
        "compile_ms": round(compile_ms, 1),
    }


def _coldstart_symbol():
    """Tiny MLP for the coldstart arms — they measure COMPILE
    accounting across process restarts, not model speed, so the
    smallest symbol with a softmax head keeps the 4 subprocess arms
    cheap."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=64, name="fc1"),
        act_type="relu")
    return mx.sym.softmax(
        mx.sym.FullyConnected(h, num_hidden=16, name="fc2"),
        name="softmax")


def bench_coldstart_worker(args):
    """One process of ``--mode coldstart`` (spawned with the cache /
    manifest wiring in env+argv; also runs standalone).  Arms:

    * ``seed``  — warmed server; populates MXNET_COMPILE_CACHE_DIR and
      captures the AOT manifest the restart arms consume.
    * ``cold``  — ``warmup=False`` restart: the first request pays the
      compile (the witness baseline).
    * ``warm``  — manifest-warmed restart (no cache): warmup compiles
      before traffic, the first request must not.
    * ``cache`` — manifest + persistent cache: warmup disk-loads, the
      first request must not compile and the cache must report hits.

    ``coldstart_compiles`` is the executor+pallas retrace delta around
    the FIRST request — the same dispatch-count witnesses every other
    mode uses, exact on any backend.  Prints one JSON line."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import aot, serving, telemetry
    from mxnet_tpu.executor import EXECUTOR_RETRACES
    from mxnet_tpu.pallas.dispatch import PALLAS_RETRACES

    sym = _coldstart_symbol()
    rng = np.random.RandomState(0)
    arg_shapes, _, _ = sym.infer_shape(data=(1, 32))
    params = {n: rng.normal(0, 0.05, s).astype(np.float32)
              for n, s in zip(sym.list_arguments(), arg_shapes)
              if n != "data"}
    arm = args.coldstart_arm

    def retraces():
        return EXECUTOR_RETRACES.value + PALLAS_RETRACES.value

    if arm == "seed":
        srv = serving.ModelServer(sym, params, {}, {"data": (32,)},
                                  max_batch_size=4, warmup=True)
        srv.predict({"data": np.zeros(32, np.float32)})
        aot.save(aot.capture(site="executor"), args.coldstart_manifest)
        srv.stop()
        print(json.dumps({
            "arm": arm,
            "programs": len(aot.load(args.coldstart_manifest)["entries"]),
        }))
        return
    manifest = args.coldstart_manifest or None
    t0 = time.perf_counter()
    srv = serving.ModelServer(sym, params, {}, {"data": (32,)},
                              max_batch_size=4, warmup=(arm != "cold"),
                              warmup_manifest=manifest)
    startup_ms = (time.perf_counter() - t0) * 1e3
    r0 = retraces()
    t1 = time.perf_counter()
    srv.predict({"data": np.zeros(32, np.float32)})
    first_ms = (time.perf_counter() - t1) * 1e3
    compiles = retraces() - r0
    warmed = sum(1 for p in telemetry.programs(analyze=False)
                 if p["warmed"])
    st = aot.stats()
    srv.stop()
    print(json.dumps({
        "arm": arm,
        "coldstart_compiles": compiles,
        "coldstart_first_step_ms": round(first_ms, 2),
        "startup_ms": round(startup_ms, 1),
        "warmed_programs": warmed,
        "cache_hits": st["cache_hits"],
        "cache_misses": st["cache_misses"],
    }))


def bench_coldstart(args):
    """Cold-start latency across process restarts (docs/AOT.md): a seed
    process populates the persistent compile cache and captures an AOT
    manifest, then three fresh subprocesses restart the same server
    cold, manifest-warmed, and manifest+cache.  Headline is the
    manifest-warmed restart's first-request latency; the hard gates
    (SystemExit) are the zero-compile contract: the cold arm must
    compile on its first request while BOTH warmed restarts serve it
    with ``coldstart_compiles == 0``, and the cache restart must
    actually disk-load (``cache_hits > 0``)."""
    import os
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    root = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="mx-coldstart-")
    manifest = os.path.join(tmp, "model.aot.json")
    cache = os.path.join(tmp, "cache")

    def run(arm, use_cache, use_manifest):
        # every arm runs under the IDENTICAL jax config (same platform,
        # same flags) — the persistent cache keys over compile options,
        # so a config fork would turn hits into silent misses
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        env.pop("MXNET_AOT_MANIFEST", None)
        env.pop("MXNET_COMPILE_CACHE_DIR", None)
        if use_cache:
            env["MXNET_COMPILE_CACHE_DIR"] = cache
        cmd = [_sys.executable, os.path.join(root, "bench.py"),
               "--mode", "coldstart-worker", "--coldstart-arm", arm]
        if use_manifest:
            cmd += ["--coldstart-manifest", manifest]
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=600)
        if proc.returncode != 0:
            raise SystemExit("bench: coldstart %s arm failed:\n%s"
                             % (arm, proc.stderr[-2000:]))
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("{") and '"arm"' in l][-1]
        return json.loads(line)

    try:
        seed = run("seed", True, True)
        cold = run("cold", False, False)
        warm = run("warm", False, True)
        cached = run("cache", True, True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if cold["coldstart_compiles"] <= 0:
        raise SystemExit(
            "bench: coldstart gate: the cold restart served its first "
            "request without compiling (%r) — the witness lost its "
            "baseline" % cold)
    for name, arm in (("manifest-warmed", warm),
                      ("persistent-cache", cached)):
        if arm["coldstart_compiles"] != 0:
            raise SystemExit(
                "bench: coldstart gate: the %s restart compiled %d "
                "program(s) on its first request (contract: 0; cold "
                "arm compiled %d)" % (name, arm["coldstart_compiles"],
                                      cold["coldstart_compiles"]))
        if arm["warmed_programs"] <= 0:
            raise SystemExit(
                "bench: coldstart gate: the %s restart registered no "
                "warmed programs in telemetry.programs() (%r)"
                % (name, arm))
    if cached["cache_hits"] <= 0:
        raise SystemExit(
            "bench: coldstart gate: the persistent-cache restart never "
            "hit the cache (%r)" % cached)
    return {
        "metric": "coldstart_first_step_ms",
        "value": warm["coldstart_first_step_ms"],
        "unit": "ms",
        "coldstart_compiles": {
            "cold": cold["coldstart_compiles"],
            "warm": warm["coldstart_compiles"],
            "cache": cached["coldstart_compiles"],
        },
        "cold_first_step_ms": cold["coldstart_first_step_ms"],
        "cache_first_step_ms": cached["coldstart_first_step_ms"],
        "startup_ms": {
            "cold": cold["startup_ms"],
            "warm": warm["startup_ms"],
            "cache": cached["startup_ms"],
        },
        "seed_programs": seed["programs"],
        "warmed_programs": warm["warmed_programs"],
        "cache_hits": cached["cache_hits"],
    }


def bench_decode(args):
    """mx.decode generative serving: continuous batching vs static
    (run-to-completion) batching over the paged-KV-cache decode engine
    (docs/DECODE.md).  Headline is ``decode_tokens_per_sec`` for the
    continuous arm; the structural witnesses are
    ``decode_dispatches_per_step`` (exactly 1 compiled launch per
    decode iteration), ``decode_retraces_steady_state`` (0 across
    ragged prompt/output lengths) and ``decode_steps_ratio_vs_static``
    (static steps / continuous steps — the dispatch-bound speedup; on
    the 1-core CPU container read the ratios, not wall times, per the
    CHANGES.md convention).  A reduced pallas-vs-xla A/B arm
    (MXNET_PAGED_ATTN_IMPL forced per run, docs/KERNELS.md) gates on
    the kernel arm keeping the same dispatch contract.

    A chunked-vs-unchunked A/B arm runs a long-prompt heavy-tailed
    mix through the engine at ``--decode-chunk`` vs an unchunked
    oracle compiled at ``--decode-seq`` (whole context in one chunk).
    Every iteration runs the ONE mixed step compiled at the engine's
    chunk width, so per-launch device work is ``capacity +
    chunk_width`` token rows **whether or not a prompt is in
    flight** — the unchunked oracle pays whole-context chunk compute
    on every decode step forever.  TTFT is therefore compared in
    launch-work units (``ttft_steps_p99 * (capacity + chunk_width)``)
    — the dispatch-count-convention stand-in for wall-clock TTFT on
    hardware, where raw iteration counts would reward fat launches
    the container can't time honestly.  Both arms' raw step counts
    are published next to the gate so nothing hides in the
    normalization."""
    import os

    import jax
    from mxnet_tpu import profiler, telemetry
    from mxnet_tpu.decode import DecodeEngine
    from mxnet_tpu.models import transformer

    cfg = dict(num_classes=args.decode_vocab, num_layers=args.decode_layers,
               d_model=args.decode_d_model, num_heads=args.decode_heads,
               seq_len=args.decode_seq)
    tsym = transformer.get_symbol(**cfg)
    arg_shapes, _, _ = tsym.infer_shape(data=(1, args.decode_seq),
                                        softmax_label=(args.decode_seq,))
    rng = np.random.RandomState(0)
    params = {n: rng.normal(0, 0.05, s).astype(np.float32)
              for n, s in zip(tsym.list_arguments(), arg_shapes)
              if n not in ("data", "softmax_label")}
    n_req = args.decode_requests
    # every request opens with the same system-prompt-style preamble
    # (the production shape prefix sharing exists for: identical
    # few-shot headers across the fleet) followed by a random tail
    sys_prompt = list(rng.randint(0, args.decode_vocab,
                                  args.decode_block_size + 1))
    prompts = [sys_prompt
               + list(rng.randint(0, args.decode_vocab,
                                  rng.randint(4,
                                              args.decode_prompt_max + 1)))
               for _ in range(n_req)]
    # heavy-tailed output lengths (many short, few near-max) — the
    # production shape continuous batching exists for; run-to-completion
    # pins every slot to its batch's longest member
    new_tokens = [4 + int((args.decode_gen_max - 4) * rng.uniform() ** 2)
                  for _ in range(n_req)]

    step_hist = telemetry.REGISTRY.get("decode_step_ms")

    def run(admission, impl=None, n=None, gen_cap=None, chunk=None,
            workload=None, spec_k=None, prefix=False):
        """One engine lifetime.  ``impl`` forces MXNET_PAGED_ATTN_IMPL
        for the whole run (the dispatch decision is baked in at trace
        time, so the env must cover engine construction + warmup);
        ``n``/``gen_cap`` shrink the workload for the interpret-mode
        pallas A/B arm, which is orders of magnitude slower off-TPU;
        ``chunk`` overrides the prefill chunk budget (the
        chunked-vs-unchunked arm), ``workload`` swaps in a different
        ``(prompts, new_tokens)`` mix, and ``spec_k``/``prefix`` arm
        draft-verify spans / COW prefix sharing (the speculative A/B
        arm) — both pinned explicitly so a stray env knob can never
        flip an arm's baseline."""
        ps, nt = (prompts, new_tokens) if workload is None else workload
        if n is not None:
            ps = ps[:n]
            nt = [min(m, gen_cap) for m in nt[:n]]
        prev = os.environ.get("MXNET_PAGED_ATTN_IMPL")
        if impl is not None:
            os.environ["MXNET_PAGED_ATTN_IMPL"] = impl
        try:
            t_c = time.perf_counter()
            eng = DecodeEngine(params, cfg, capacity=args.decode_capacity,
                               block_size=args.decode_block_size,
                               num_blocks=args.decode_blocks,
                               max_waiting=n_req + 1, admission=admission,
                               chunk_tokens=(chunk if chunk is not None
                                             else args.decode_chunk),
                               spec_k=(spec_k if spec_k is not None else 0),
                               prefix_cache=prefix, warmup=True)
            compile_ms = (time.perf_counter() - t_c) * 1e3
            try:
                snap0 = (step_hist.snapshot()
                         if step_hist is not None else None)
                d0 = profiler.DEVICE_DISPATCHES.value
                t0 = time.perf_counter()
                handles = [eng.submit(p, max_new_tokens=m)
                           for p, m in zip(ps, nt)]
                streams = [h.result(timeout=600) for h in handles]
                toks = sum(len(s) for s in streams)
                dt = time.perf_counter() - t0
                st = eng.stats()
                st["_tokens"] = toks
                st["_streams"] = streams
                st["_dt"] = dt
                st["_dispatches"] = profiler.DEVICE_DISPATCHES.value - d0
                st["_compile_ms"] = compile_ms
                if step_hist is not None and snap0 is not None:
                    st["_p50"] = telemetry.hist_quantile(
                        step_hist.snapshot(), 0.5, since=snap0)
                    st["_p99"] = telemetry.hist_quantile(
                        step_hist.snapshot(), 0.99, since=snap0)
            finally:
                eng.stop()
            return st
        finally:
            if impl is not None:
                if prev is None:
                    os.environ.pop("MXNET_PAGED_ATTN_IMPL", None)
                else:
                    os.environ["MXNET_PAGED_ATTN_IMPL"] = prev

    cont = run("continuous")
    static = run("static")
    # pallas-vs-xla A/B arm on a reduced workload (same engine
    # geometry).  Forcing impl=pallas off-TPU is legal because the
    # kernels run interpret=True anywhere; wall-clock is meaningless
    # there, so the gate is structural: the kernel arm must keep the
    # one-launch-per-step contract and stay retrace-free.
    n_ab = min(6, n_req)
    ab_xla = run("continuous", impl="xla", n=n_ab, gen_cap=6)
    ab_pallas = run("continuous", impl="pallas", n=n_ab, gen_cap=6)
    if (ab_pallas["dispatches_per_step"] != 1.0
            or ab_pallas["steady_state_retraces"] != 0):
        raise SystemExit(
            "decode pallas arm broke the dispatch contract: "
            "dispatches_per_step=%r (want 1.0), "
            "steady_state_retraces=%r (want 0)"
            % (ab_pallas["dispatches_per_step"],
               ab_pallas["steady_state_retraces"]))
    # chunked-vs-unchunked A/B arm (docstring): a long-prompt
    # heavy-tailed mix — many short prompts, a heavy tail reaching
    # most of the context window — served at the production chunk
    # budget vs an unchunked oracle whose every launch carries a
    # max_context-wide chunk stream
    ab_rng = np.random.RandomState(7)
    ck_prompts, ck_gens = [], []
    long_lo = max(args.decode_seq // 2, 8)
    long_hi = max(args.decode_seq - 12, long_lo + 1)
    for _ in range(min(10, n_req)):
        plen = (ab_rng.randint(long_lo, long_hi)
                if ab_rng.uniform() < 0.4 else ab_rng.randint(4, 13))
        ck_prompts.append(list(ab_rng.randint(0, args.decode_vocab,
                                              plen)))
        ck_gens.append(2 + int(ab_rng.randint(0, 5)))
    ck_wl = (ck_prompts, ck_gens)
    ab_chunked = run("continuous", workload=ck_wl)
    ab_unchunked = run("continuous", chunk=args.decode_seq,
                       workload=ck_wl)
    if (ab_chunked["dispatches_per_step"] != 1.0
            or ab_chunked["steady_state_retraces"] != 0):
        raise SystemExit(
            "decode chunked arm broke the dispatch contract: "
            "dispatches_per_step=%r (want 1.0), "
            "steady_state_retraces=%r (want 0)"
            % (ab_chunked["dispatches_per_step"],
               ab_chunked["steady_state_retraces"]))
    if ab_chunked["_streams"] != ab_unchunked["_streams"]:
        raise SystemExit("chunked arm diverged from the unchunked "
                         "full-prefill oracle (greedy streams differ)")

    # speculative A/B arm (docs/DECODE.md): the SAME heavy-tailed mix
    # with draft-verify spans on vs off (the `cont` arm IS the spec-off
    # baseline — identical engine geometry and workload).  Greedy
    # acceptance must keep the streams oracle-identical; the structural
    # gates pin the one-launch / zero-retrace contract; and
    # tokens_per_launch > 1 is the feature's existence proof — the
    # n-gram drafter must land SOME accepted spans on this mix.
    spec_on = run("continuous", spec_k=args.decode_spec_k, prefix=True)
    if spec_on["_streams"] != cont["_streams"]:
        raise SystemExit("speculative arm diverged from the "
                         "non-speculative oracle (greedy streams differ)")
    if (spec_on["dispatches_per_step"] != 1.0
            or spec_on["steady_state_retraces"] != 0):
        raise SystemExit(
            "decode speculative arm broke the dispatch contract: "
            "dispatches_per_step=%r (want 1.0), "
            "steady_state_retraces=%r (want 0)"
            % (spec_on["dispatches_per_step"],
               spec_on["steady_state_retraces"]))
    if not (spec_on["tokens_per_launch"] or 0) > 1.0:
        raise SystemExit(
            "decode speculative arm committed no extra tokens: "
            "tokens_per_launch=%r (want > 1.0; accept_rate=%r, "
            "proposed=%r)" % (spec_on["tokens_per_launch"],
                              spec_on["accept_rate"],
                              spec_on["spec_proposed"]))
    if not spec_on["cache"]["prefix_hit_blocks"] > 0:
        raise SystemExit(
            "prefix sharing never hit: every request carries the same "
            "system preamble, so later admissions must adopt trie "
            "blocks (prefix_hit_blocks=%r)"
            % spec_on["cache"]["prefix_hit_blocks"])

    def _ttft_work(st):
        # per-launch token rows: C decode rows + the compiled chunk
        # width every launch carries, prompt in flight or not
        return st["ttft_steps_p99"] * (args.decode_capacity
                                       + st["chunk_tokens"])

    if not _ttft_work(ab_chunked) < _ttft_work(ab_unchunked):
        raise SystemExit(
            "chunked prefill did not improve launch-work TTFT p99 "
            "under the long-prompt mix: chunked %r (steps %r x width "
            "%r) vs unchunked %r (steps %r x width %r)"
            % (_ttft_work(ab_chunked), ab_chunked["ttft_steps_p99"],
               args.decode_capacity + ab_chunked["chunk_tokens"],
               _ttft_work(ab_unchunked),
               ab_unchunked["ttft_steps_p99"],
               args.decode_capacity + ab_unchunked["chunk_tokens"]))
    # the mixed-step compiled program, recognized by its block-table
    # feed [capacity, table_width] (recorded arg_shapes truncate at 8
    # entries and the donated order puts the cache arrays first, so
    # the (C, 1) token input can fall outside the recorded prefix —
    # the block table survives both argument orders); bytes_accessed
    # is the donation acceptance witness — the donated step no longer
    # pays the whole-cache in+out copy
    fn_want = ("_fwd_eval_donated" if cont.get("cache_donation")
               else "_fwd_eval")
    table_w = -(-args.decode_seq // args.decode_block_size)
    step_rows = [p for p in telemetry.programs(site="executor")
                 if p["fn_name"] == fn_want
                 and any(s.endswith("[%d, %d]" % (args.decode_capacity,
                                                  table_w))
                         for s in p["arg_shapes"])]
    decode_bytes = max((p["bytes_accessed"] for p in step_rows
                        if p["bytes_accessed"] is not None), default=None)
    dev = jax.devices()[0]
    out = {
        "metric": "decode_tokens_per_sec",
        "value": round(cont["_tokens"] / cont["_dt"], 1),
        "unit": "tok/s",
        "device_kind": dev.device_kind,
        "config": {"layers": args.decode_layers,
                   "d_model": args.decode_d_model,
                   "heads": args.decode_heads, "vocab": args.decode_vocab,
                   "capacity": args.decode_capacity,
                   "block_size": args.decode_block_size,
                   "num_blocks": args.decode_blocks,
                   "requests": n_req},
        "decode_ttft_p99_ms": _round_opt(cont["ttft_p99_ms"]),
        "decode_cache_occupancy": _round_opt(cont["mean_cache_occupancy"]),
        "decode_slot_occupancy": _round_opt(
            cont["mean_slot_occupancy"] / args.decode_capacity
            if cont["mean_slot_occupancy"] else None),
        "decode_dispatches_per_step": _round_opt(
            cont["dispatches_per_step"]),
        "decode_dispatches_per_token": _round_opt(
            cont["_dispatches"] / cont["_tokens"]),
        "decode_retraces_steady_state": cont["steady_state_retraces"],
        "decode_preemptions": cont["preemptions"],
        "decode_steps": cont["steps"],
        "decode_chunk_tokens": cont["chunk_tokens"],
        "decode_prefill_chunks_per_iter": _round_opt(
            cont["prefill_chunks_per_iter"]),
        "decode_ttft_steps_p99": cont["ttft_steps_p99"],
        "decode_chunked_ttft_steps_p99": ab_chunked["ttft_steps_p99"],
        "decode_unchunked_ttft_steps_p99":
            ab_unchunked["ttft_steps_p99"],
        "decode_chunked_ttft_work_p99": _ttft_work(ab_chunked),
        "decode_unchunked_ttft_work_p99": _ttft_work(ab_unchunked),
        "decode_attn_impl": cont.get("attn_impl"),
        "decode_cache_donation": cont.get("cache_donation"),
        "decode_bytes_accessed": decode_bytes,
        "decode_pallas_dispatches_per_step": _round_opt(
            ab_pallas["dispatches_per_step"]),
        "decode_pallas_retraces_steady_state":
            ab_pallas["steady_state_retraces"],
        "decode_ab_tokens_equal":
            ab_pallas["_streams"] == ab_xla["_streams"],
        # speculative arm: stream identity is gated above; steps ratio
        # is the dispatch-bound speedup speculation buys on this mix
        "decode_spec_k": args.decode_spec_k,
        "decode_spec_impl": spec_on.get("spec_impl"),
        "decode_accept_rate": _round_opt(spec_on["accept_rate"]),
        "decode_tokens_per_launch": _round_opt(
            spec_on["tokens_per_launch"]),
        "decode_spec_steps_ratio": round(
            cont["steps"] / max(spec_on["steps"], 1), 2),
        "decode_prefix_hit_blocks":
            spec_on["cache"]["prefix_hit_blocks"],
        "static_tokens_per_sec": round(
            static["_tokens"] / static["_dt"], 1),
        "static_steps": static["steps"],
        # wall-clock speedup (noisy on the 1-core container) AND the
        # dispatch-count form that transfers to the ~100 ms/launch
        # tunneled-TPU harness: each step is one launch, so the step
        # ratio IS the dispatch-bound tokens/s ratio
        "decode_speedup_vs_static": round(
            (cont["_tokens"] / cont["_dt"])
            / (static["_tokens"] / static["_dt"]), 2),
        "decode_steps_ratio_vs_static": round(
            static["steps"] / max(cont["steps"], 1), 2),
    }
    out["step_ms_p50"] = _round_opt(cont.get("_p50"))
    out["step_ms_p99"] = _round_opt(cont.get("_p99"))
    out["compile_ms"] = _round_opt(cont["_compile_ms"], 1)
    return out


def bench_fleet(args):
    """mx.fleet disaggregated serving (docs/FLEET.md): three arms.

    * **Routing A/B** — the SAME shared-prefix request mix (three
      request families, each opening with its own system preamble,
      interleaved round-robin the way a fleet actually sees traffic)
      through a two-replica ``FleetRouter`` under ``affinity`` vs
      ``least_loaded``.  Hard gate: the affinity arm's summed
      ``prefix_hit_blocks`` must be STRICTLY higher — co-locating a
      family on one replica converts every repeat preamble into trie
      hits, while spreading makes each replica re-prefill it.
    * **TP arm** — ``make_tp_engine(tensor_parallel=2)`` over the mp
      mesh must keep the decode contract intact (1 dispatch/iteration,
      0 steady-state retraces, greedy streams bit-identical to the
      single-device baseline) while its per-device cache bytes drop to
      <= 0.6x replicated — TP buys memory, never different math.
    * **Scale-up arm** — a COLD replica (``warmup=False``) joins the
      ring via ``add_replica`` (which AOT-warms BEFORE the replica is
      routable) and serves its first routed request with ZERO
      serve-time compiles (``steady_state_retraces == 0``).

    Wall-clock is meaningless for routing on the 1-core container; the
    headline is the hit-block ratio, the dispatch-count convention's
    stand-in for the TTFT win prefix affinity buys on hardware."""
    import os
    import sys
    if "jax" not in sys.modules \
            and os.environ.get("JAX_PLATFORMS") == "cpu":
        # standalone --mode fleet on the CPU container: the TP arm
        # needs >= 2 visible devices (same knob tests/conftest.py pins)
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    from mxnet_tpu import sharding
    from mxnet_tpu.decode import DecodeEngine
    from mxnet_tpu.fleet import (FleetRouter, make_tp_engine,
                                 per_device_cache_bytes)
    from mxnet_tpu.models import transformer

    cfg = dict(num_classes=args.decode_vocab,
               num_layers=args.decode_layers, d_model=16,
               num_heads=2, seq_len=args.decode_seq)
    ek = dict(capacity=4, block_size=args.decode_block_size,
              num_blocks=args.decode_blocks, chunk_tokens=8,
              warmup=True, prefix_cache=True)
    tsym = transformer.get_symbol(**cfg)
    shapes, _, _ = tsym.infer_shape(data=(1, args.decode_seq),
                                    softmax_label=(args.decode_seq,))
    rng = np.random.RandomState(0)
    params = {n: rng.normal(0, 0.05, s).astype(np.float32)
              for n, s in zip(tsym.list_arguments(), shapes)
              if n not in ("data", "softmax_label")}

    # three request FAMILIES (distinct system preambles spanning > 3
    # full cache blocks) interleaved round-robin: the shape
    # prefix-affinity routing exists for — without stickiness or
    # affinity, consecutive arrivals from one family land on different
    # replicas and every one re-prefills the preamble
    fam_rng = np.random.RandomState(11)
    preambles = [list(fam_rng.randint(0, args.decode_vocab,
                                      3 * args.decode_block_size + 1))
                 for _ in range(3)]
    requests = []
    for turn in range(4):
        for fam, pre in enumerate(preambles):
            requests.append(pre + list(fam_rng.randint(
                0, args.decode_vocab, 2 + fam + turn)))

    def run_router_arm(policy):
        engs = {"r0": DecodeEngine(params, cfg, **ek),
                "r1": DecodeEngine(params, cfg, **ek)}
        try:
            router = FleetRouter(policy=policy, sticky=False,
                                 trie_blocks=4096)
            for name, eng in engs.items():
                router.add_replica(name, eng)
            placements = []
            for toks in requests:
                name, eng = router.route(toks)
                placements.append(name)
                eng.generate(toks, max_new_tokens=4, timeout=300)
            hit_blocks = sum(
                e.stats()["cache"]["prefix_hit_blocks"]
                for e in engs.values())
            return {"hit_blocks": int(hit_blocks),
                    "spread": len(set(placements)),
                    "router": router.stats()}
        finally:
            for eng in engs.values():
                eng.stop()

    affinity = run_router_arm("affinity")
    least = run_router_arm("least_loaded")
    if not affinity["hit_blocks"] > least["hit_blocks"]:
        raise SystemExit(
            "bench: affinity routing did not beat least_loaded on "
            "prefix_hit_blocks (%d vs %d) under the shared-prefix "
            "mix — cache-aware placement bought nothing"
            % (affinity["hit_blocks"], least["hit_blocks"]))

    # TP arm: same prompts single-device vs mp=2
    tp_prompts = [list(fam_rng.randint(0, args.decode_vocab,
                                       fam_rng.randint(4, 13)))
                  for _ in range(4)]
    base = DecodeEngine(params, cfg, **ek)
    try:
        base_streams = [base.generate(p, max_new_tokens=8, timeout=300)
                        for p in tp_prompts]
        base_bytes = per_device_cache_bytes(base)
    finally:
        base.stop()
    n_dev = len(jax.devices())
    if n_dev >= 2 and n_dev % 2 == 0:
        try:
            tp = make_tp_engine(params, cfg, tensor_parallel=2, **ek)
            try:
                tp_streams = [tp.generate(p, max_new_tokens=8,
                                          timeout=300)
                              for p in tp_prompts]
                tp_stats = tp.stats()
                tp_bytes = per_device_cache_bytes(tp)
            finally:
                tp.stop()
        finally:
            sharding.clear_mesh()
        if tp_streams != base_streams:
            raise SystemExit("bench: TP decode arm changed the greedy "
                             "streams vs the single-device baseline")
        if (tp_stats["dispatches_per_step"] != 1.0
                or tp_stats["steady_state_retraces"] != 0):
            raise SystemExit(
                "bench: TP decode arm broke the dispatch contract: "
                "dispatches_per_step=%r (want 1.0), "
                "steady_state_retraces=%r (want 0)"
                % (tp_stats["dispatches_per_step"],
                   tp_stats["steady_state_retraces"]))
        cache_ratio = round(tp_bytes / max(1, base_bytes), 3)
        if cache_ratio > 0.6:
            raise SystemExit(
                "bench: TP per-device cache bytes %d = %.0f%% of "
                "replicated %d (want <= 60%%) — the head shards "
                "silently replicated" % (tp_bytes, 100 * cache_ratio,
                                         base_bytes))
        tp_fields = {
            "fleet_tp_dispatches_per_step":
                tp_stats["dispatches_per_step"],
            "fleet_tp_retraces_steady_state":
                tp_stats["steady_state_retraces"],
            "fleet_tp_cache_bytes_ratio": cache_ratio,
        }
    else:
        tp_fields = {"fleet_tp_note":
                     "%d visible device(s): mp=2 needs an even "
                     "count >= 2" % n_dev}

    # scale-up arm: a cold replica joins and serves compile-free
    cold = DecodeEngine(params, cfg, capacity=4,
                        block_size=args.decode_block_size,
                        num_blocks=args.decode_blocks, chunk_tokens=8,
                        warmup=False, prefix_cache=True)
    try:
        router = FleetRouter(policy="affinity", sticky=False)
        warmed = router.add_replica("join", cold)
        name, eng = router.route(tp_prompts[0])
        eng.generate(tp_prompts[0], max_new_tokens=6, timeout=300)
        join_stats = eng.stats()
    finally:
        cold.stop()
    if warmed <= 0 or join_stats["steady_state_retraces"] != 0:
        raise SystemExit(
            "bench: scale-up first request compiled at serve time "
            "(warmed=%r, steady_state_retraces=%r — want > 0 / 0): "
            "add_replica must AOT-warm before ring insertion"
            % (warmed, join_stats["steady_state_retraces"]))

    dev = jax.devices()[0]
    out = {
        "metric": "fleet_affinity_hit_ratio",
        "value": round(affinity["hit_blocks"]
                       / max(1, least["hit_blocks"]), 2),
        "unit": "x",
        "device_kind": dev.device_kind,
        "config": {"replicas": 2, "requests": len(requests),
                   "families": len(preambles),
                   "block_size": args.decode_block_size,
                   "num_blocks": args.decode_blocks,
                   "vocab": args.decode_vocab,
                   "seq": args.decode_seq},
        "fleet_affinity_hit_blocks": affinity["hit_blocks"],
        "fleet_least_loaded_hit_blocks": least["hit_blocks"],
        "fleet_affinity_replicas_used": affinity["spread"],
        "fleet_least_loaded_replicas_used": least["spread"],
        "fleet_router_mirror_blocks": sum(
            r["mirror_blocks"]
            for r in affinity["router"]["replicas"].values()),
        "fleet_scale_up_warmed_programs": warmed,
        "fleet_scale_up_retraces_first_request":
            join_stats["steady_state_retraces"],
    }
    out.update(tp_fields)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", type=str, default="all",
                    choices=["all", "resnet", "transformer"])
    ap.add_argument("--mode", type=str, default="train",
                    choices=["train", "inference", "serving", "checkpoint",
                             "kvstore", "kvstore-mh-worker",
                             "fit", "decode", "dlrm", "dlrm-part-worker",
                             "transformer", "fleet",
                             "coldstart", "coldstart-worker"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image-shape", type=str, default="3,224,224")
    ap.add_argument("--layout", type=str, default="NHWC",
                    choices=["NCHW", "NHWC"])
    ap.add_argument("--dtype", type=str, default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--num-layers", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--pipeline", action="store_true",
                    help="feed the resnet step from a real ImageRecordIter "
                         "over a generated .rec of JPEGs (threaded native "
                         "decode + augment + prefetch) instead of "
                         "device-resident synthetic batches")
    ap.add_argument("--decode-threads", type=int, default=8)
    ap.add_argument("--fuse", dest="fuse", action="store_true", default=False,
                    help="apply the BN→ReLU→Conv1×1 Pallas fusion pass "
                         "(NHWC only; A/B flag — see docs/PERF.md for the "
                         "measured result)")
    ap.add_argument("--no-fuse", dest="fuse", action="store_false")
    ap.add_argument("--pipeline-scaling", action="store_true",
                    help="measure host decode throughput at 1/2/4/8 "
                         "threads (iterator only, no device)")
    ap.add_argument("--quantized", action="store_true",
                    help="with --mode inference: calibrated int8/uint8 "
                         "ResNet-50 scoring (ops/quantization_ops.py)")
    # mx.serving throughput (--mode serving; also folded into the default
    # run as serving_* fields so BENCH_* tracks it alongside training)
    ap.add_argument("--serving-requests", type=int, default=256)
    ap.add_argument("--serving-clients", type=int, default=4)
    ap.add_argument("--serving-replicas", type=int, default=1)
    ap.add_argument("--serving-max-batch", type=int, default=8)
    ap.add_argument("--serving-latency-ms", type=float, default=5.0)
    # coldstart bench (--mode coldstart; also folded into the default
    # line as coldstart_compiles / coldstart_first_step_ms)
    ap.add_argument("--coldstart-arm", type=str, default="cold",
                    choices=["seed", "cold", "warm", "cache"],
                    help="which --mode coldstart-worker arm this "
                         "process runs (set by the parent)")
    ap.add_argument("--coldstart-manifest", type=str, default="",
                    help="AOT manifest path shared between the "
                         "coldstart seed and restart arms")
    # kvstore bench (--mode kvstore; also folded into the default line)
    ap.add_argument("--kv-ndev", type=int, default=4,
                    help="simulated per-key device gradient streams for "
                         "the kvstore bench (the CommDevice reduce width)")
    ap.add_argument("--kv-hosts", type=int, default=2,
                    help="process count of the kvstore='tpu' multi-host "
                         "arm (spawned via tools/run_multihost.py; 1 "
                         "skips the arm)")
    # fused fit step witnesses (--mode fit; also folded into the default
    # line as train_dispatches_per_step / host_syncs_per_step)
    ap.add_argument("--fit-batch", type=int, default=4)
    ap.add_argument("--fit-image-shape", type=str, default="3,224,224")
    ap.add_argument("--fit-steps", type=int, default=4)
    ap.add_argument("--ckpt-saves", type=int, default=4,
                    help="checkpoint saves per arm in --mode checkpoint")
    # mx.decode generative-serving bench (--mode decode; also folded
    # into the default line as decode_* fields). NOTE: --decode-threads
    # above is the IMAGE-decode pipeline knob, unrelated.
    ap.add_argument("--decode-requests", type=int, default=32)
    ap.add_argument("--decode-capacity", type=int, default=8,
                    help="decode batch slots (compiled step batch dim)")
    ap.add_argument("--decode-block-size", type=int, default=8,
                    help="KV-cache tokens per block")
    ap.add_argument("--decode-blocks", type=int, default=64,
                    help="KV-cache blocks per layer")
    ap.add_argument("--decode-layers", type=int, default=2)
    ap.add_argument("--decode-d-model", type=int, default=64)
    ap.add_argument("--decode-heads", type=int, default=4)
    ap.add_argument("--decode-vocab", type=int, default=128)
    ap.add_argument("--decode-seq", type=int, default=64,
                    help="max context (position-embedding range)")
    ap.add_argument("--decode-prompt-max", type=int, default=12)
    ap.add_argument("--decode-gen-max", type=int, default=40)
    ap.add_argument("--decode-chunk", type=int, default=8,
                    help="prefill chunk budget (tokens/iteration); the "
                         "chunked-vs-unchunked A/B arm compares against "
                         "an oracle compiled at --decode-seq")
    ap.add_argument("--decode-spec-k", type=int, default=4,
                    help="draft tokens per slot for the speculative "
                         "A/B arm (spec-on vs spec-off under the same "
                         "heavy-tailed mix; stream-identity gated)")
    # transformer-LM config (sized for one v5e chip at bf16)
    ap.add_argument("--lm-batch", type=int, default=4)
    ap.add_argument("--lm-seq", type=int, default=1024)
    ap.add_argument("--lm-layers", type=int, default=12)
    ap.add_argument("--lm-d-model", type=int, default=2048)
    ap.add_argument("--lm-heads", type=int, default=16)
    ap.add_argument("--lm-vocab", type=int, default=16384)

    ap.add_argument("--dlrm-vocab", type=int, default=4096,
                    help="rows per categorical feature (the stacked "
                         "table is dlrm-features * dlrm-vocab rows)")
    ap.add_argument("--dlrm-dim", type=int, default=64)
    ap.add_argument("--dlrm-features", type=int, default=8)
    ap.add_argument("--dlrm-batch", type=int, default=128,
                    help="batch * features must be a power of two "
                         "(single-dispatch lookup)")
    ap.add_argument("--dlrm-hosts", type=int, default=2,
                    help="process count of the pod-partitioned "
                         "embedding arm (spawned via "
                         "tools/run_multihost.py; 1 skips the arm)")
    args = ap.parse_args()

    if args.pipeline_scaling:
        print(json.dumps(bench_pipeline_scaling(args)))
        return
    if args.mode == "serving":
        print(json.dumps(bench_serving(args)))
        return
    if args.mode == "kvstore":
        print(json.dumps(bench_kvstore(args)))
        return
    if args.mode == "kvstore-mh-worker":
        bench_kvstore_mh_worker(args)
        return
    if args.mode == "dlrm":
        print(json.dumps(bench_dlrm(args)))
        return
    if args.mode == "dlrm-part-worker":
        bench_dlrm_part_worker(args)
        return
    if args.mode == "fit":
        print(json.dumps(bench_fit(args)))
        return
    if args.mode == "transformer":
        print(json.dumps(bench_transformer_mp(args)))
        return
    if args.mode == "decode":
        print(json.dumps(bench_decode(args)))
        return
    if args.mode == "fleet":
        print(json.dumps(bench_fleet(args)))
        return
    if args.mode == "checkpoint":
        print(json.dumps(bench_checkpoint(args)))
        return
    if args.mode == "coldstart":
        print(json.dumps(bench_coldstart(args)))
        return
    if args.mode == "coldstart-worker":
        bench_coldstart_worker(args)
        return
    if args.mode == "inference":
        if args.quantized:
            print(json.dumps(bench_quantized_inference(args)))
            return
        print(json.dumps(bench_inference(args)))
        return
    if args.pipeline and args.model == "transformer":
        raise SystemExit("--pipeline is the ResNet image-input mode; "
                         "combine it with --model resnet (or all)")
    if args.model == "transformer":
        print(json.dumps(bench_transformer(args)))
        return
    if args.model == "resnet" or args.pipeline:
        print(json.dumps(bench_resnet(args)))
        return
    # default: resnet headline + transformer_* + serving_* fields, one
    # JSON line (BENCH_* tracks serving throughput alongside training)
    out = bench_resnet(args)
    lm = bench_transformer(args)
    out["transformer_tokens_per_sec"] = lm["value"]
    out["transformer_mfu"] = lm["mfu"]
    out["transformer_mfu_measured"] = lm["mfu_measured"]
    out["transformer_achieved_tflops"] = lm["achieved_tflops"]
    out["transformer_config"] = lm["config"]
    sv = bench_serving(args)
    out["serving_qps"] = sv["value"]
    out["serving_mean_batch_occupancy"] = sv["mean_batch_occupancy"]
    out["serving_latency_p99_ms"] = sv["latency_p99_ms"]
    kvb = bench_kvstore(args)
    out["kvstore_push_pull_gbps"] = kvb["value"]
    out["kvstore_speedup_vs_eager"] = kvb["speedup_vs_eager"]
    out["kvstore_compress_ratio"] = kvb["kvstore_compress_ratio"]
    out["kvstore_hosts"] = kvb["kvstore_hosts"]
    out["crosshost_bytes_per_step"] = kvb["crosshost_bytes_per_step"]
    fit = bench_fit(args)
    out["train_dispatches_per_step"] = fit["train_dispatches_per_step"]
    out["host_syncs_per_step"] = fit["host_syncs_per_step"]
    out["fit_step_ms"] = fit["fit_step_ms"]
    out["sentinel_overhead_pct"] = fit["sentinel_overhead_pct"]
    out["sentinel_alerts"] = fit["sentinel_alerts"]
    tmp = bench_transformer_mp(args)
    out["transformer_mp"] = tmp.get("transformer_mp")
    out["param_bytes_per_device"] = tmp.get("param_bytes_per_device")
    out["sharding_constraint_sites"] = tmp.get("sharding_constraint_sites")
    cp = bench_checkpoint(args)
    out["checkpoint_block_ms"] = cp["value"]
    out["checkpoint_save_ms"] = cp["checkpoint_save_ms"]
    out["checkpoint_bytes"] = cp["checkpoint_bytes"]
    dc = bench_decode(args)
    out["decode_tokens_per_sec"] = dc["value"]
    out["decode_ttft_p99_ms"] = dc["decode_ttft_p99_ms"]
    out["decode_chunk_tokens"] = dc["decode_chunk_tokens"]
    out["decode_prefill_chunks_per_iter"] = \
        dc["decode_prefill_chunks_per_iter"]
    out["decode_ttft_steps_p99"] = dc["decode_ttft_steps_p99"]
    out["decode_cache_occupancy"] = dc["decode_cache_occupancy"]
    out["decode_dispatches_per_step"] = dc["decode_dispatches_per_step"]
    out["decode_speedup_vs_static"] = dc["decode_speedup_vs_static"]
    out["decode_steps_ratio_vs_static"] = dc["decode_steps_ratio_vs_static"]
    out["decode_attn_impl"] = dc["decode_attn_impl"]
    out["decode_bytes_accessed"] = dc["decode_bytes_accessed"]
    out["decode_spec_k"] = dc["decode_spec_k"]
    out["decode_accept_rate"] = dc["decode_accept_rate"]
    out["decode_tokens_per_launch"] = dc["decode_tokens_per_launch"]
    cs = bench_coldstart(args)
    out["coldstart_compiles"] = cs["coldstart_compiles"]
    out["coldstart_first_step_ms"] = cs["value"]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
