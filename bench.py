"""Headline benchmark: ResNet-50 ImageNet training throughput.

Reference baseline (BASELINE.md / docs/faq/perf.md:205-215): MXNet 1.2
ResNet-50 training, batch 32, fp32, 1x V100 = 298.51 img/s.

Here the whole training step — forward, backward, gradient scale, SGD
momentum update — is ONE XLA computation (parallel/trainer.py TrainStep)
running bf16 on the MXU with fp32 master weights (the multi-precision
configuration the reference exposes as optimizer.py SGD multi_precision).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline",
"device_kind", "achieved_tflops", "peak_bf16_tflops", "mfu"}.
See docs/PERF.md for the trace-backed roofline analysis: this model is
HBM-bandwidth-bound on TPU (~26% MFU ≈ the chip's practical ceiling for
ResNet-50/224 with BatchNorm; matches MLPerf per-chip numbers scaled by
memory bandwidth).
"""
import argparse
import json
import time

import numpy as np


BASELINE_IMG_PER_SEC = 298.51

# Peak bf16 TFLOP/s per chip, keyed by substrings of jax device_kind.
# MFU = achieved model FLOP/s over this peak.
_PEAK_TFLOPS = [
    ("v6", 918.0),      # Trillium
    ("v5p", 459.0),
    ("v5", 197.0),      # v5e / "v5 lite"
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]


def _peak_tflops(device_kind):
    kind = device_kind.lower()
    for key, peak in _PEAK_TFLOPS:
        if key in kind:
            return peak
    return None


def _make_pipeline_stream(args, image_shape):
    """Endless DataBatch stream from a generated .rec of JPEG images
    (PrefetchingIter over ImageRecordIter with the native decode path)."""
    import io as _pyio
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import recordio
    from PIL import Image

    c, h, w = image_shape
    n_images = max(2 * args.batch, 256)
    d = tempfile.mkdtemp(prefix="bench_rec_")
    rec_path = d + "/bench.rec"
    idx_path = d + "/bench.idx"
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(n_images):
        img = rng.randint(0, 255, (h, w, c), dtype=np.uint8)
        buf = _pyio.BytesIO()
        Image.fromarray(img.squeeze() if c == 1 else img).save(
            buf, "JPEG", quality=90)
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.getvalue()))
    rec.close()

    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path,
        data_shape=image_shape, batch_size=args.batch, shuffle=True,
        rand_mirror=True, mean_r=127.0, mean_g=127.0, mean_b=127.0,
        std_r=64.0, std_g=64.0, std_b=64.0,
        preprocess_threads=args.decode_threads)
    it = mx.io.PrefetchingIter(it)

    def stream():
        while True:
            it.reset()
            for batch in it:
                yield batch

    return stream()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image-shape", type=str, default="3,224,224")
    ap.add_argument("--dtype", type=str, default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--num-layers", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--pipeline", action="store_true",
                    help="feed the step from a real ImageRecordIter over "
                         "a generated .rec of JPEGs (threaded native "
                         "decode + augment + prefetch) instead of "
                         "device-resident synthetic batches")
    ap.add_argument("--decode-threads", type=int, default=8)
    args = ap.parse_args()

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import TrainStep

    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    sym = models.get_symbol("resnet", num_classes=1000,
                            num_layers=args.num_layers,
                            image_shape=image_shape, dtype=args.dtype)
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                           multi_precision=(args.dtype != "float32"),
                           rescale_grad=1.0 / args.batch)
    ts = TrainStep(sym, opt,
                   data_shapes={"data": (args.batch,) + image_shape},
                   label_shapes={"softmax_label": (args.batch,)})
    ts.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                  magnitude=2))

    # Synthetic device-resident batches (the reference's perf.md numbers are
    # synthetic-data benchmarks of the training step; input-pipeline overlap
    # is the data iterator's job, not the step's). Two batches alternate to
    # avoid any single-buffer artifacts.
    import jax.numpy as jnp
    rng = np.random.RandomState(0)

    if args.pipeline:
        # real input pipeline: a generated .rec of JPEGs decoded by the
        # native threaded path, augmented + prefetched, host->device per
        # step — shows the step is not input-bound (VERDICT weak #9;
        # the reference's perf.md numbers are synthetic-only).
        stream = _make_pipeline_stream(args, image_shape)

        def next_batch(_i):
            b = next(stream)
            return {"data": b.data[0].asnumpy(),
                    "softmax_label": b.label[0].asnumpy()}
    else:
        batches = []
        for _ in range(2):
            data = jnp.asarray(rng.uniform(
                -1, 1, (args.batch,) + image_shape).astype(np.float32))
            label = jnp.asarray(rng.randint(0, 1000, (args.batch,))
                                .astype(np.float32))
            batches.append({"data": data, "softmax_label": label})
        jax.block_until_ready(batches)

        def next_batch(i):
            return batches[i % 2]

    for i in range(args.warmup):
        outs = ts.step(next_batch(i))
    jax.block_until_ready(ts.params)

    # FLOPs of the compiled step from XLA's cost model (covers fwd+bwd+
    # optimizer as actually compiled); fallback: the analytic ResNet-50
    # estimate of ~24.6 GFLOP per image for training (3x the 8.2 GFLOP =
    # 4.1 GMAC forward).
    flops_per_step = None
    try:
        lowered = ts._step_fn.lower(
            ts.params, ts.states, ts.auxs, batches[0],
            jnp.float32(0.1), np.uint32(0))
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops_per_step = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass
    if flops_per_step is None and args.num_layers == 50:
        # ResNet-50 fwd ≈ 4.1 GMACs = 8.2 GFLOP/img; training ≈ 3x fwd
        flops_per_step = 24.6e9 * args.batch

    t0 = time.perf_counter()
    for i in range(args.iters):
        outs = ts.step(next_batch(i))
    jax.block_until_ready(ts.params)
    dt = time.perf_counter() - t0

    img_per_sec = args.batch * args.iters / dt
    dev = jax.devices()[0]
    peak = _peak_tflops(dev.device_kind)
    achieved_tflops = (flops_per_step * args.iters / dt / 1e12
                       if flops_per_step else None)
    mfu = (round(achieved_tflops / peak, 4)
           if achieved_tflops and peak else None)
    print(json.dumps({
        "metric": ("resnet50_train_img_per_sec_pipeline" if args.pipeline
                   else "resnet50_train_img_per_sec"),
        "value": round(img_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "device_kind": dev.device_kind,
        "achieved_tflops": round(achieved_tflops, 2) if achieved_tflops else None,
        "peak_bf16_tflops": peak,
        "mfu": mfu,
    }))


if __name__ == "__main__":
    main()
