"""Headline benchmark: ResNet-50 ImageNet training throughput.

Reference baseline (BASELINE.md / docs/faq/perf.md:205-215): MXNet 1.2
ResNet-50 training, batch 32, fp32, 1x V100 = 298.51 img/s.

Here the whole training step — forward, backward, gradient scale, SGD
momentum update — is ONE XLA computation (parallel/trainer.py TrainStep)
running bf16 on the MXU with fp32 master weights (the multi-precision
configuration the reference exposes as optimizer.py SGD multi_precision).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline",
"device_kind", "achieved_tflops", "peak_bf16_tflops", "mfu"}.
See docs/PERF.md for the trace-backed roofline analysis: this model is
HBM-bandwidth-bound on TPU (~26% MFU ≈ the chip's practical ceiling for
ResNet-50/224 with BatchNorm; matches MLPerf per-chip numbers scaled by
memory bandwidth).
"""
import argparse
import json
import time

import numpy as np


BASELINE_IMG_PER_SEC = 298.51

# Peak bf16 TFLOP/s per chip, keyed by substrings of jax device_kind.
# MFU = achieved model FLOP/s over this peak.
_PEAK_TFLOPS = [
    ("v6", 918.0),      # Trillium
    ("v5p", 459.0),
    ("v5", 197.0),      # v5e / "v5 lite"
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]


def _peak_tflops(device_kind):
    kind = device_kind.lower()
    for key, peak in _PEAK_TFLOPS:
        if key in kind:
            return peak
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image-shape", type=str, default="3,224,224")
    ap.add_argument("--dtype", type=str, default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--num-layers", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import TrainStep

    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    sym = models.get_symbol("resnet", num_classes=1000,
                            num_layers=args.num_layers,
                            image_shape=image_shape, dtype=args.dtype)
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                           multi_precision=(args.dtype != "float32"),
                           rescale_grad=1.0 / args.batch)
    ts = TrainStep(sym, opt,
                   data_shapes={"data": (args.batch,) + image_shape},
                   label_shapes={"softmax_label": (args.batch,)})
    ts.init_params(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                  magnitude=2))

    # Synthetic device-resident batches (the reference's perf.md numbers are
    # synthetic-data benchmarks of the training step; input-pipeline overlap
    # is the data iterator's job, not the step's). Two batches alternate to
    # avoid any single-buffer artifacts.
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    batches = []
    for _ in range(2):
        data = jnp.asarray(rng.uniform(
            -1, 1, (args.batch,) + image_shape).astype(np.float32))
        label = jnp.asarray(rng.randint(0, 1000, (args.batch,))
                            .astype(np.float32))
        batches.append({"data": data, "softmax_label": label})
    jax.block_until_ready(batches)

    for i in range(args.warmup):
        outs = ts.step(batches[i % 2])
    jax.block_until_ready(ts.params)

    # FLOPs of the compiled step from XLA's cost model (covers fwd+bwd+
    # optimizer as actually compiled); fallback: the analytic ResNet-50
    # estimate of ~24.6 GFLOP per image for training (3x the 8.2 GFLOP =
    # 4.1 GMAC forward).
    flops_per_step = None
    try:
        lowered = ts._step_fn.lower(
            ts.params, ts.states, ts.auxs, batches[0],
            jnp.float32(0.1), np.uint32(0))
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops_per_step = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass
    if flops_per_step is None and args.num_layers == 50:
        # ResNet-50 fwd ≈ 4.1 GMACs = 8.2 GFLOP/img; training ≈ 3x fwd
        flops_per_step = 24.6e9 * args.batch

    t0 = time.perf_counter()
    for i in range(args.iters):
        outs = ts.step(batches[i % 2])
    jax.block_until_ready(ts.params)
    dt = time.perf_counter() - t0

    img_per_sec = args.batch * args.iters / dt
    dev = jax.devices()[0]
    peak = _peak_tflops(dev.device_kind)
    achieved_tflops = (flops_per_step * args.iters / dt / 1e12
                       if flops_per_step else None)
    mfu = (round(achieved_tflops / peak, 4)
           if achieved_tflops and peak else None)
    print(json.dumps({
        "metric": "resnet50_train_img_per_sec",
        "value": round(img_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "device_kind": dev.device_kind,
        "achieved_tflops": round(achieved_tflops, 2) if achieved_tflops else None,
        "peak_bf16_tflops": peak,
        "mfu": mfu,
    }))


if __name__ == "__main__":
    main()
