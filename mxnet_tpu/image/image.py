"""Image decode / resize / crop / augment, and the Python ImageIter.

Reference parity: python/mxnet/image/image.py. The reference decodes and
augments through OpenCV NDArray ops on the engine; here everything is
host-side numpy + PIL (the TPU is busy running the training step — the
data pipeline's job is to hide under it). Channel order is RGB
everywhere. Augmenter classes keep the reference API: they take and
return ``NDArray`` (numpy also accepted); the hot RecordIO path calls
their ``_apply_np`` directly to stay off-device.
"""
from __future__ import annotations

import io as _pyio
import logging
import os
import random as _pyrandom

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

try:
    from PIL import Image
except ImportError:  # pragma: no cover
    Image = None


def _require_pil():
    if Image is None:  # pragma: no cover
        raise MXNetError("mx.image requires PIL (Pillow)")


# interp codes follow cv2 / the reference (_get_interp_method,
# image.py:175): 0 nearest, 1 bilinear, 2 area, 3 bicubic, 4 lanczos,
# 9 auto (cubic enlarge / area shrink), 10 random
_PIL_INTERP = {}


def _interp(interp, src_size=None, dst_size=None):
    _require_pil()
    if not _PIL_INTERP:
        _PIL_INTERP.update({0: Image.NEAREST, 1: Image.BILINEAR,
                            2: Image.BOX, 3: Image.BICUBIC,
                            4: Image.LANCZOS})
    if interp == 9:
        if src_size and dst_size:
            oh, ow = src_size
            nh, nw = dst_size
            return _PIL_INTERP[3 if nh > oh and nw > ow else 2]
        return _PIL_INTERP[2]
    if interp == 10:
        return _PIL_INTERP[_pyrandom.randint(0, 4)]
    if interp not in _PIL_INTERP:
        raise ValueError("unknown interp method %s" % interp)
    return _PIL_INTERP[interp]


def _to_np(src):
    if isinstance(src, NDArray):
        return src.asnumpy()
    return np.asarray(src)


def _wrap(out, like):
    if isinstance(like, NDArray) or not isinstance(like, np.ndarray):
        return NDArray(np.ascontiguousarray(out))
    return out


# ----------------------------------------------------------------------
# decode / resize / crop primitives
# ----------------------------------------------------------------------
def _imdecode_np(buf, flag=1):
    """Decode to an HWC uint8 NUMPY array — the decode-thread hot path
    (ImageRecordIter). JPEG content takes the native libjpeg path
    (src/jpeg.cc — GIL-free, mirroring the reference's C++ OpenCV decode
    in iter_image_recordio_2.cc:480); everything else goes through PIL.
    Staying in numpy here matters: wrapping per-image results in
    NDArrays would bounce every image through the accelerator."""
    from .._native import native_jpeg_decode
    arr = native_jpeg_decode(buf, gray=not flag)
    if arr is None:
        _require_pil()
        img = Image.open(_pyio.BytesIO(bytes(buf)))
        img = img.convert("RGB" if flag else "L")
        arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an encoded image buffer to an HWC uint8 NDArray (reference
    image.py:86). Output is RGB regardless of to_rgb — the reference
    flag exists to flip cv2's BGR, which neither backend produces."""
    nd = NDArray(_imdecode_np(buf, flag))
    if out is not None:
        out._set_data(nd._data)
        return out
    return nd


def imread(filename, flag=1, to_rgb=True):
    """Read an image file into an HWC uint8 NDArray (reference
    image.py:45)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=2):
    """Resize to exactly (h, w) (reference mx.image cv2 imresize op)."""
    arr = _to_np(src)
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    pil = Image.fromarray(arr[:, :, 0] if squeeze else arr)
    pil = pil.resize((int(w), int(h)),
                     _interp(interp, arr.shape[:2], (h, w)))
    out = np.asarray(pil)
    if out.ndim == 2:
        out = out[:, :, None]
    return _wrap(out, src)


def scale_down(src_size, size):
    """Scale (w, h) down to fit within src (w, h), keeping aspect
    (reference image.py:140)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge equals ``size`` (reference
    image.py:230)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop [y0:y0+h, x0:x0+w], optionally resize to ``size`` (w, h)
    (reference image.py:292)."""
    arr = _to_np(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(_wrap(out, src), size[0], size[1], interp)
    return _wrap(out, src)


def random_crop(src, size, interp=2):
    """Random crop of ``size`` (w, h), scaled down if src is smaller;
    returns (img, (x0, y0, w, h)) (reference image.py:324)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Center crop of ``size`` (w, h); returns (img, (x0, y0, w, h))
    (reference image.py:363)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """(src - mean) / std in float32 (reference image.py:412)."""
    arr = _to_np(src).astype(np.float32)
    if mean is not None:
        arr = arr - _to_np(mean).astype(np.float32)
    if std is not None:
        arr = arr / _to_np(std).astype(np.float32)
    return _wrap(arr, src)


def random_size_crop(src, size, area, ratio, interp=2, **kwargs):
    """Random crop with area in ``area`` (fraction) and aspect in
    ``ratio``; returns (img, (x0, y0, w, h)) (reference image.py:436)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if "min_area" in kwargs:
        area = kwargs.pop("min_area")
    assert not kwargs, "unexpected keyword arguments %s" % list(kwargs)
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


# ----------------------------------------------------------------------
# augmenters (reference image.py:493+); each works on numpy HWC float32
# via _apply_np, the NDArray __call__ is the API-parity wrapper
# ----------------------------------------------------------------------
class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in kwargs.items():
            if isinstance(v, NDArray):
                kwargs[k] = v.asnumpy()

    def dumps(self):
        """Name + params as a json-ish string (reference Augmenter.dumps)."""
        import json
        return json.dumps([self.__class__.__name__.lower(),
                           {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                            for k, v in self._kwargs.items()}])

    def _apply_np(self, src):
        raise NotImplementedError

    def __call__(self, src):
        return _wrap(self._apply_np(_to_np(src)), src)


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def _apply_np(self, src):
        for t in self.ts:
            src = t._apply_np(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def _apply_np(self, src):
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t._apply_np(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def _apply_np(self, src):
        return _to_np(resize_short(src, self.size, self.interp))


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def _apply_np(self, src):
        return _to_np(imresize(src, self.size[0], self.size[1], self.interp))


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def _apply_np(self, src):
        return _to_np(random_crop(src, self.size, self.interp)[0])


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, interp

    def _apply_np(self, src):
        return _to_np(random_size_crop(src, self.size, self.area,
                                       self.ratio, self.interp)[0])


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def _apply_np(self, src):
        return _to_np(center_crop(src, self.size, self.interp)[0])


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def _apply_np(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return src.astype(np.float32) * alpha


class ContrastJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def _apply_np(self, src):
        src = src.astype(np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (src * self._coef[..., :src.shape[2]]).sum()
        gray = (3.0 * (1.0 - alpha) / src.size) * gray
        return src * alpha + gray


class SaturationJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def _apply_np(self, src):
        src = src.astype(np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (src * self._coef[..., :src.shape[2]]).sum(
            axis=2, keepdims=True) * (1.0 - alpha)
        return src * alpha + gray


class HueJitterAug(Augmenter):
    # yiq rotation matrices (reference image.py:747)
    _tyiq = np.array([[0.299, 0.587, 0.114],
                      [0.596, -0.274, -0.321],
                      [0.211, -0.523, 0.311]], dtype=np.float32)
    _ityiq = np.array([[1.0, 0.956, 0.621],
                       [1.0, -0.272, -0.647],
                       [1.0, -1.107, 1.705]], dtype=np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def _apply_np(self, src):
        src = src.astype(np.float32)
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]], dtype=np.float32)
        t = self._ityiq @ bt @ self._tyiq
        return src @ t.T


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """AlexNet-style PCA noise (reference image.py:804)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd, eigval=eigval, eigvec=eigvec)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def _apply_np(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,)).astype(np.float32)
        rgb = self.eigvec @ (alpha * self.eigval)
        return src.astype(np.float32) + rgb


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = None if mean is None else np.asarray(_to_np(mean), np.float32)
        self.std = None if std is None else np.asarray(_to_np(std), np.float32)

    def _apply_np(self, src):
        src = src.astype(np.float32)
        if self.mean is not None:
            src = src - self.mean
        if self.std is not None:
            src = src / self.std
        return src


class RandomGrayAug(Augmenter):
    _mat = np.array([[0.21, 0.21, 0.21],
                     [0.72, 0.72, 0.72],
                     [0.07, 0.07, 0.07]], dtype=np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def _apply_np(self, src):
        if _pyrandom.random() < self.p:
            return src.astype(np.float32) @ self._mat
        return src


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def _apply_np(self, src):
        if _pyrandom.random() < self.p:
            return src[:, ::-1]
        return src


class VerticalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def _apply_np(self, src):
        if _pyrandom.random() < self.p:
            return src[::-1]
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def _apply_np(self, src):
        return src.astype(self.typ)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Create the standard augmenter list (reference image.py:903)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))

    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))

    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())

    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))

    if mean is True:
        mean = np.array([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375], np.float32)
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# ----------------------------------------------------------------------
# ImageIter — python-side image iterator (reference image.py:1017)
# ----------------------------------------------------------------------
class ImageIter:
    """Iterator over images from a .rec file, a .lst file, or an in-memory
    list, with augmenters (reference image.py ImageIter). Yields
    DataBatch(data=[NCHW float32], label=[(N, label_width)]).
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 last_batch_handle="pad", **kwargs):
        from ..io import DataDesc
        assert path_imgrec or path_imglist or isinstance(imglist, list), \
            "must provide path_imgrec, path_imglist, or imglist"
        assert len(data_shape) == 3 and data_shape[0] in (1, 3)
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.dtype = dtype
        self.last_batch_handle = last_batch_handle
        self._data_name, self._label_name = data_name, label_name

        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            from ..recordio import MXRecordIO, MXIndexedRecordIO
            if path_imgidx:
                self.imgrec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = MXRecordIO(path_imgrec, "r")
                if shuffle:
                    raise MXNetError(
                        "shuffle requires path_imgidx alongside path_imgrec")
        elif path_imglist:
            self.imglist = {}
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = np.array(
                        [float(x) for x in parts[1:-1]], np.float32)
                    self.imglist[int(parts[0])] = (label, parts[-1])
            self.seq = sorted(self.imglist.keys())
            self.path_root = path_root or "."
        else:
            self.imglist = {}
            for i, (label, fname) in enumerate(imglist):
                self.imglist[i] = (np.array(np.atleast_1d(label), np.float32),
                                   fname)
            self.seq = list(range(len(imglist)))
            self.path_root = path_root or "."

        if self.seq is not None and num_parts > 1:
            n = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n:(part_index + 1) * n]

        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list

        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape, dtype)]
        self.provide_label = [DataDesc(label_name,
                                       (batch_size, label_width), dtype)]
        self.cur = 0
        self._allow_read = True
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            _pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0
        self._allow_read = True

    def next_sample(self):
        """Next (label, decoded HWC uint8 image)."""
        from ..recordio import unpack
        if not self._allow_read:
            raise StopIteration
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as f:
                return label, f.read()
        s = self.imgrec.read()
        if s is None:
            self._allow_read = False
            raise StopIteration
        header, img = unpack(s)
        return header.label, img

    def _aug(self, raw):
        img = _imdecode_np(raw, flag=1 if self.data_shape[0] == 3 else 0)
        for aug in self.auglist:
            img = aug._apply_np(img)
        c, h, w = self.data_shape
        if img.shape[:2] != (h, w):
            raise MXNetError("augmented image shape %s does not match "
                             "data_shape %s (add a crop/resize augmenter)"
                             % (img.shape, self.data_shape))
        return np.ascontiguousarray(
            img.astype(self.dtype).transpose(2, 0, 1))

    def next(self):
        from ..io import DataBatch
        from .. import ndarray as nd
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), self.dtype)
        label = np.zeros((self.batch_size, self.label_width), self.dtype)
        i = 0
        try:
            while i < self.batch_size:
                lab, raw = self.next_sample()
                data[i] = self._aug(raw)
                label[i] = np.atleast_1d(np.asarray(lab, np.float32))[
                    :self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            if self.last_batch_handle == "discard":
                raise
        pad = self.batch_size - i
        from ..context import cpu
        # host-resident batches (reference iterator contract;
        # consumers move them to the bind device exactly once)
        return DataBatch(data=[nd.array(data, ctx=cpu())],
                         label=[nd.array(label, ctx=cpu())],
                         pad=pad)

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self

    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            return False
