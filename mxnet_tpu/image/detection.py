"""Detection data iterator + box-aware augmenters.

Reference parity: python/mxnet/image/detection.py (ImageDetIter:625 and
the Det* augmenters) + src/io/iter_image_det_recordio.cc. Labels follow
the reference wire format: per image a flat float array
``[header_width, object_width, <header...>, (id, x1, y1, x2, y2)...]``
with normalized corner coords; batches pad the object dimension with -1
rows to the epoch-wide max. Augmentations transform boxes together with
pixels (crop clips + renormalizes, flip mirrors x), all host-side numpy
like the rest of mx.image.
"""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

from ..base import MXNetError
from . import image as _img

__all__ = ["ImageDetIter", "DetHorizontalFlipAug", "DetRandomCropAug",
           "DetBorderAug", "CreateDetAugmenter"]


def _parse_det_label(raw):
    """Flat reference label -> (K, 1+4+extra) object array
    (reference detection.py:723 _check_valid_label)."""
    raw = _np.asarray(raw, _np.float32).ravel()
    if raw.size >= 2 and raw.size > int(raw[0]):
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if header_width >= 2 and obj_width >= 5 \
                and (raw.size - header_width) % obj_width == 0:
            return raw[header_width:].reshape(-1, obj_width)
    # plain (id, x1, y1, x2, y2)* fallback
    if raw.size % 5 == 0:
        return raw.reshape(-1, 5)
    raise MXNetError("invalid detection label of size %d" % raw.size)


class DetAugmenter:
    def __call__(self, src, label):
        raise NotImplementedError


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes with probability p (reference
    detection.py DetHorizontalFlipAug)."""

    def __init__(self, p):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = src[:, ::-1]
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
        return src, label


class DetBorderAug(DetAugmenter):
    """Pad the image with a filled border, rescaling boxes (reference
    DetRandomPadAug simplified to a fixed expansion)."""

    def __init__(self, expand=1.5, fill=127):
        self.expand = float(expand)
        self.fill = fill

    def __call__(self, src, label):
        h, w = src.shape[:2]
        nh, nw = int(h * self.expand), int(w * self.expand)
        oy = _pyrandom.randint(0, nh - h)
        ox = _pyrandom.randint(0, nw - w)
        out = _np.full((nh, nw) + src.shape[2:], self.fill, src.dtype)
        out[oy:oy + h, ox:ox + w] = src
        label = label.copy()
        label[:, 1] = (label[:, 1] * w + ox) / nw
        label[:, 3] = (label[:, 3] * w + ox) / nw
        label[:, 2] = (label[:, 2] * h + oy) / nh
        label[:, 4] = (label[:, 4] * h + oy) / nh
        return out, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping enough box overlap; boxes are clipped and
    renormalized, fully-cropped-out boxes dropped (reference
    DetRandomCropAug, min_object_covered semantics simplified)."""

    def __init__(self, min_crop_scale=0.6, min_object_covered=0.3,
                 max_attempts=10):
        self.min_crop_scale = min_crop_scale
        self.min_object_covered = min_object_covered
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            s = _pyrandom.uniform(self.min_crop_scale, 1.0)
            cw, ch = int(w * s), int(h * s)
            x0 = _pyrandom.randint(0, w - cw)
            y0 = _pyrandom.randint(0, h - ch)
            new = self._crop_boxes(label, x0, y0, cw, ch, w, h)
            if len(new):
                return src[y0:y0 + ch, x0:x0 + cw], new
        return src, label

    def _crop_boxes(self, label, x0, y0, cw, ch, w, h):
        out = []
        for row in label:
            bx1, by1, bx2, by2 = (row[1] * w, row[2] * h,
                                  row[3] * w, row[4] * h)
            ix1, iy1 = max(bx1, x0), max(by1, y0)
            ix2, iy2 = min(bx2, x0 + cw), min(by2, y0 + ch)
            inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
            area = max((bx2 - bx1) * (by2 - by1), 1e-8)
            if inter / area < self.min_object_covered:
                continue
            new = row.copy()
            new[1] = (ix1 - x0) / cw
            new[2] = (iy1 - y0) / ch
            new[3] = (ix2 - x0) / cw
            new[4] = (iy2 - y0) / ch
            out.append(new)
        return _np.asarray(out, _np.float32).reshape(-1, label.shape[1])


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None, brightness=0,
                       contrast=0, saturation=0, inter_method=2, **kwargs):
    """Standard detection augmenter list (reference
    detection.py CreateDetAugmenter). Pixel-only augmenters wrap the
    mx.image classes; geometric ones are box-aware."""
    auglist = []
    if rand_crop > 0:
        auglist.append(DetRandomCropAug())
    if rand_pad > 0:
        auglist.append(DetBorderAug())
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))

    pixel = []
    if brightness or contrast or saturation:
        pixel.append(_img.ColorJitterAug(brightness, contrast, saturation))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53], _np.float32)
    if std is True:
        std = _np.array([58.395, 57.12, 57.375], _np.float32)
    if mean is not None or std is not None:
        pixel.append(_img.ColorNormalizeAug(mean, std))

    class _PixelWrap(DetAugmenter):
        # pixel-only augs leave boxes untouched AND may produce float
        # arrays, so ImageDetIter runs them after the final resize
        pixel = True

        def __init__(self, aug):
            self.aug = aug

        def __call__(self, src, label):
            return self.aug._apply_np(src), label

    auglist.extend(_PixelWrap(a) for a in pixel)
    return auglist


class ImageDetIter(_img.ImageIter):
    """ImageIter for detection: labels are padded object arrays
    (reference detection.py:625)."""

    _ITER_KWARGS = ("label_width", "part_index", "num_parts", "dtype")
    _SCAN_LIMIT = 512

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="label",
                 last_batch_handle="pad", label_shape=None, **kwargs):
        iter_kwargs = {k: kwargs.pop(k) for k in list(kwargs)
                       if k in self._ITER_KWARGS}
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **kwargs)
        elif kwargs:
            raise MXNetError("unexpected arguments with explicit "
                             "aug_list: %s" % sorted(kwargs))
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         path_imgidx=path_imgidx, shuffle=shuffle,
                         aug_list=[], imglist=imglist, data_name=data_name,
                         label_name=label_name,
                         last_batch_handle=last_batch_handle,
                         **iter_kwargs)
        self.det_auglist = aug_list
        if label_shape is not None:
            self._max_objects = int(label_shape[0])
            self._obj_width = int(label_shape[1])
        else:
            self._max_objects = self._scan_max_objects()
        from ..io import DataDesc
        self.provide_label = [DataDesc(label_name,
                                       (batch_size, self._max_objects,
                                        self._obj_width), "float32")]

    def _scan_max_objects(self):
        """Estimate the object pad width from the first _SCAN_LIMIT
        labels (the reference sizes via ``label_shape``; pass it
        explicitly for exact control — an image exceeding the estimate
        raises at iteration, never silently truncates)."""
        from ..recordio import unpack
        max_obj, obj_w = 1, 5

        def see(raw):
            nonlocal max_obj, obj_w
            lab = _parse_det_label(raw)
            max_obj = max(max_obj, len(lab))
            obj_w = max(obj_w, lab.shape[1])

        if self.imgrec is not None and self.seq is not None:
            for idx in self.seq[:self._SCAN_LIMIT]:
                header, _ = unpack(self.imgrec.read_idx(idx))
                see(header.label)
        elif self.imgrec is not None:
            for _ in range(self._SCAN_LIMIT):
                s = self.imgrec.read()
                if s is None:
                    break
                see(unpack(s)[0].label)
            self.imgrec.reset()
        elif self.imglist is not None:
            for label, _ in list(self.imglist.values())[:self._SCAN_LIMIT]:
                see(label)
        self._obj_width = obj_w
        return max_obj

    def next(self):
        from ..io import DataBatch
        from .. import ndarray as nd
        c, h, w = self.data_shape
        data = _np.zeros((self.batch_size, c, h, w), _np.float32)
        label = _np.full((self.batch_size, self._max_objects,
                          self._obj_width), -1.0, _np.float32)
        i = 0
        try:
            while i < self.batch_size:
                lab, raw = self.next_sample()
                img = _img._imdecode_np(raw)
                objs = _parse_det_label(lab)
                # geometric (box-aware) augs on uint8, then resize, then
                # pixel-only augs (they may produce float, which the
                # PIL-backed resize cannot take)
                for aug in self.det_auglist:
                    if not getattr(aug, "pixel", False):
                        img, objs = aug(img, objs)
                img = _img._to_np(_img.imresize(img, w, h))
                for aug in self.det_auglist:
                    if getattr(aug, "pixel", False):
                        img, objs = aug(img, objs)
                img = img.astype(_np.float32)
                data[i] = img.transpose(2, 0, 1)
                if len(objs) > self._max_objects:
                    raise MXNetError(
                        "image has %d objects but label pad width is %d "
                        "— pass label_shape=(max_objects, %d)"
                        % (len(objs), self._max_objects, self._obj_width))
                if len(objs):
                    label[i, :len(objs)] = objs
                i += 1
        except StopIteration:
            if i == 0:
                raise
            if self.last_batch_handle == "discard":
                raise
        from ..context import cpu
        # host-resident batches (reference iterator contract;
        # consumers move them to the bind device exactly once)
        return DataBatch(data=[nd.array(data, ctx=cpu())],
                         label=[nd.array(label, ctx=cpu())],
                         pad=self.batch_size - i,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
