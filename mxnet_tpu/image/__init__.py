"""mx.image — image IO, augmentation, and iterators (reference
python/mxnet/image/ + src/io/image_aug_default.cc, rebuilt host-side in
numpy/PIL; the decode/augment pipeline is host work by design — TPU time
is for the training step, and the iterators overlap the two)."""
from .image import (imread, imdecode, imresize, scale_down, resize_short,
                    fixed_crop, random_crop, center_crop, color_normalize,
                    random_size_crop,
                    Augmenter, SequentialAug, RandomOrderAug, ResizeAug,
                    ForceResizeAug, RandomCropAug, RandomSizedCropAug,
                    CenterCropAug, BrightnessJitterAug, ContrastJitterAug,
                    SaturationJitterAug, HueJitterAug, ColorJitterAug,
                    LightingAug, ColorNormalizeAug, RandomGrayAug,
                    HorizontalFlipAug, VerticalFlipAug, CastAug,
                    CreateAugmenter, ImageIter)
from .record_iter import ImageRecordIter
from .detection import (ImageDetIter, CreateDetAugmenter,
                        DetHorizontalFlipAug, DetRandomCropAug,
                        DetBorderAug)
