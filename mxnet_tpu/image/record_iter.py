"""ImageRecordIter — the fast RecordIO image pipeline.

Reference parity: src/io/iter_image_recordio_2.cc:50-762
(ImageRecordIter2: record reader → OMP-parallel JPEG decode + augment →
batch → prefetch). TPU-native shape: a thread pool decodes/augments
(PIL releases the GIL in its C paths), a producer thread assembles
batches, and a bounded queue prefetches ``prefetch_buffer`` batches
ahead so host image work hides under device step time. Output batches
are NCHW host arrays; Module/TrainStep move them to HBM.

Accepted parameters mirror ImageRecParserParam / ImageRecordParam /
ImageNormalizeParam / PrefetcherParam (src/io/image_recordio*.cc);
unknown kwargs warn and are ignored (the reference tolerates the union
of all its param structs).
"""
from __future__ import annotations

import logging
import queue as _queue
import random as _pyrandom
import struct
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..base import MXNetError
from . import image as _img

__all__ = ["ImageRecordIter"]

_KNOWN_IGNORED = {
    "verbose", "aug_seq", "shuffle_chunk_size", "shuffle_chunk_seed",
    "max_rotate_angle", "max_shear_ratio", "max_img_size", "min_img_size",
    "mean_a", "std_a", "pad", "rotate", "seed_aug", "device_id",
    "max_random_contrast", "max_random_illumination", "num_threads",
}


class ImageRecordIter:
    """Threaded RecordIO image iterator (see module docstring)."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, label_width=1,
                 shuffle=False, seed=0, part_index=0, num_parts=1,
                 preprocess_threads=4, prefetch_buffer=4, round_batch=True,
                 resize=-1, rand_crop=False, rand_mirror=False, mirror=False,
                 random_resized_crop=False,
                 max_random_area=1.0, min_random_area=1.0,
                 max_aspect_ratio=0.0, min_aspect_ratio=None,
                 max_random_scale=1.0, min_random_scale=1.0,
                 max_crop_size=-1, min_crop_size=-1,
                 brightness=0.0, contrast=0.0, saturation=0.0,
                 pca_noise=0.0, random_h=0, random_s=0, random_l=0,
                 mean_img=None, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 fill_value=255, inter_method=1, dtype="float32",
                 data_name="data", label_name="softmax_label", ctx=None,
                 **kwargs):
        from ..io import DataDesc
        for k in kwargs:
            if k not in _KNOWN_IGNORED:
                logging.warning("ImageRecordIter: ignoring unsupported "
                                "parameter '%s'", k)
        data_shape = tuple(int(x) for x in data_shape)
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (channels, height, width)")
        self.batch_size = int(batch_size)
        self.data_shape = data_shape
        self.label_width = int(label_width)
        self.dtype = dtype
        self._shuffle = bool(int(shuffle)) if not isinstance(shuffle, bool) \
            else shuffle
        self._round_batch = bool(int(round_batch)) \
            if not isinstance(round_batch, bool) else round_batch
        self._rng = _pyrandom.Random(seed or None)
        self._nthreads = max(1, int(preprocess_threads))
        self._prefetch = max(1, int(prefetch_buffer))

        # augmentation config
        self._resize = int(resize)
        self._rand_crop = _truthy(rand_crop)
        self._rand_mirror = _truthy(rand_mirror)
        self._mirror = _truthy(mirror)
        self._rrc = _truthy(random_resized_crop)
        self._area = (float(min_random_area), float(max_random_area))
        mar = float(max_aspect_ratio)
        if min_aspect_ratio is None:
            # legacy aspect jitter: ratio in [1-mar, 1+mar] (image_aug_default.cc)
            self._ratio = (max(1.0 - mar, 1e-3), 1.0 + mar)
        else:
            self._ratio = (float(min_aspect_ratio), mar if mar > 0 else 4. / 3.)
        self._scale_rng = (float(min_random_scale), float(max_random_scale))
        self._jitter = (float(brightness), float(contrast), float(saturation))
        self._pca_noise = float(pca_noise)
        self._hsl = (float(random_h), float(random_s), float(random_l))
        self._inter = int(inter_method)
        self._out_scale = float(scale)

        c = data_shape[0]
        mean = None
        if mean_img:
            try:
                from ..ndarray import load as _nd_load
                mean = list(_nd_load(mean_img).values())[0].asnumpy()
            except Exception:
                logging.warning("ImageRecordIter: could not load mean_img "
                                "%s; falling back to mean_rgb", mean_img)
        if mean is None and (mean_r or mean_g or mean_b):
            mean = np.array([mean_r, mean_g, mean_b][:c], np.float32)
        self._mean = mean
        std = np.array([std_r, std_g, std_b][:c], np.float32)
        self._std = std if np.any(std != 1.0) else None

        # native mmap reader when available (src/recordio.cc): one shared
        # zero-copy mapping across the decode threads; falls back to the
        # pure-Python per-thread file readers
        self._native = None
        try:
            from .._native import NativeRecordReader
            self._native = NativeRecordReader(path_imgrec)
        except OSError:
            pass

        # index the .rec so shuffle/partition never needs a separate pass
        from ..recordio import MXIndexedRecordIO
        if path_imgidx:
            rec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            offsets = [rec.idx[k] for k in rec.keys]
            rec.close()
        elif self._native is not None:
            offsets = self._native.scan_offsets()
        else:
            offsets = _scan_offsets(path_imgrec)
        n = len(offsets) // num_parts if num_parts > 1 else len(offsets)
        if num_parts > 1:
            offsets = offsets[part_index * n:(part_index + 1) * n]
        if not offsets:
            raise MXNetError("no records found in %s" % path_imgrec)
        self._offsets = offsets
        self._path = path_imgrec

        self.provide_data = [DataDesc(data_name,
                                      (self.batch_size,) + data_shape, dtype)]
        lshape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        self.provide_label = [DataDesc(label_name, lshape, dtype)]

        self._pool = ThreadPoolExecutor(max_workers=self._nthreads)
        self._tls = threading.local()
        self._queue = None
        self._producer = None
        self._epoch_stop = None
        self.reset()

    # ------------------------------------------------------------------
    def _reader(self):
        fp = getattr(self._tls, "fp", None)
        if fp is None:
            fp = open(self._path, "rb")
            self._tls.fp = fp
        return fp

    def _read_at(self, offset):
        """Read one record's payload at a byte offset (native mmap or
        thread-local fp)."""
        native = self._native
        if native is not None:
            return native.read_at(offset)
        fp = self._reader()
        fp.seek(offset)
        parts = []
        while True:
            head = fp.read(8)
            magic, lrec = struct.unpack("<II", head)
            cflag, length = lrec >> 29, lrec & ((1 << 29) - 1)
            data = fp.read(length)
            pad = (-length) % 4
            if pad:
                fp.read(pad)
            parts.append(data)
            if cflag in (0, 3):
                return b"".join(parts)

    def _process(self, offset):
        """record → (HWC float32 image, label vector); runs in the pool.
        Batch-level normalize + CHW layout happen in _produce."""
        from ..recordio import unpack
        header, raw = unpack(self._read_at(offset))
        c, h, w = self.data_shape
        # numpy end to end: no NDArray (= accelerator) round trips per
        # image inside the decode pool
        img = _img._imdecode_np(raw, flag=1 if c == 3 else 0)

        if self._resize > 0:
            img = _img._to_np(_img.resize_short(img, self._resize,
                                                self._inter))
        smin, smax = self._scale_rng
        if smax != 1.0 or smin != 1.0:
            s = self._rng.uniform(smin, smax)
            ih, iw = img.shape[:2]
            img = _img._to_np(_img.imresize(
                img, max(int(iw * s), w), max(int(ih * s), h), self._inter))

        if self._rrc:
            img = _img._to_np(_img.random_size_crop(
                img, (w, h), self._area, self._ratio, self._inter)[0])
        elif self._rand_crop:
            img = _img._to_np(_img.random_crop(img, (w, h), self._inter)[0])
        else:
            img = _img._to_np(_img.center_crop(img, (w, h), self._inter)[0])

        if self._mirror or (self._rand_mirror and self._rng.random() < 0.5):
            img = img[:, ::-1]

        img = img.astype(np.float32)
        b, ct, s = self._jitter
        if b:
            img *= 1.0 + self._rng.uniform(-b, b)
        if ct:
            alpha = 1.0 + self._rng.uniform(-ct, ct)
            coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)
            gray = (img * coef[..., :img.shape[2]]).sum()
            img = img * alpha + (3.0 * (1.0 - alpha) / img.size) * gray
        if s:
            alpha = 1.0 + self._rng.uniform(-s, s)
            coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)
            gray = (img * coef[..., :img.shape[2]]).sum(axis=2, keepdims=True)
            img = img * alpha + gray * (1.0 - alpha)
        rh, rs, rl = self._hsl
        if rh or rs or rl:
            img = _hsl_jitter(img, self._rng, rh, rs, rl)
        if self._pca_noise > 0:
            eigval = np.array([55.46, 4.794, 1.148], np.float32)
            eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                               [-0.5808, -0.0045, -0.8140],
                               [-0.5836, -0.6948, 0.4203]], np.float32)
            alpha = np.random.normal(0, self._pca_noise, 3).astype(np.float32)
            img = img + eigvec @ (alpha * eigval)

        # mean/std/scale + HWC->CHW happen ON THE BATCH in _produce —
        # one big vectorized numpy op instead of per-image passes
        label = np.atleast_1d(np.asarray(header.label, np.float32))
        return img, label[:self.label_width]

    # ------------------------------------------------------------------
    def _produce(self, order, out_q, stop):
        try:
            bs = self.batch_size
            for start in range(0, len(order), bs):
                if stop.is_set():
                    return
                idxs = order[start:start + bs]
                pad = bs - len(idxs)
                if pad:
                    if not self._round_batch:
                        break
                    idxs = idxs + order[:pad]  # wrap (reference round_batch)
                futs = [self._pool.submit(self._process, self._offsets[i])
                        for i in idxs]
                c, h, w = self.data_shape
                hwc = np.empty((bs, h, w, c), np.float32)
                if self.label_width == 1:
                    label = np.empty((bs,), self.dtype)
                else:
                    label = np.empty((bs, self.label_width), self.dtype)
                for j, f in enumerate(futs):
                    img, lab = f.result()
                    hwc[j] = img
                    label[j] = lab if self.label_width > 1 else lab[0]
                # batch-level normalize + layout: one vectorized pass
                if self._mean is not None:
                    hwc -= (self._mean if self._mean.ndim > 1 else
                            self._mean.reshape(1, 1, 1, -1))
                if self._std is not None:
                    hwc /= self._std.reshape(1, 1, 1, -1)
                if self._out_scale != 1.0:
                    hwc *= self._out_scale
                data = np.ascontiguousarray(
                    hwc.transpose(0, 3, 1, 2)).astype(self.dtype,
                                                      copy=False)
                out_q.put(("batch", data, label, pad))
            out_q.put(("end",))
        except BaseException as e:  # surface worker errors at next()
            out_q.put(("error", e))

    def reset(self):
        if self._epoch_stop is not None:
            self._epoch_stop.set()
            # drain so the old producer can exit
            try:
                while True:
                    self._queue.get_nowait()
            except _queue.Empty:
                pass
        order = list(range(len(self._offsets)))
        if self._shuffle:
            self._rng.shuffle(order)
        self._queue = _queue.Queue(maxsize=self._prefetch)
        self._epoch_stop = threading.Event()
        self._producer = threading.Thread(
            target=self._produce, args=(order, self._queue, self._epoch_stop),
            daemon=True)
        self._producer.start()

    def next(self):
        from ..io import DataBatch
        from .. import ndarray as nd
        from ..context import cpu
        item = self._queue.get()
        if item[0] == "end":
            raise StopIteration
        if item[0] == "error":
            raise item[1]
        _, data, label, pad = item
        # batches live on the HOST (cpu context), like the reference's
        # iterators: the training step moves them to the accelerator
        # exactly once — yielding device arrays here would force an
        # upload+download round trip on any consumer that reads them
        return DataBatch(data=[nd.array(data, ctx=cpu())],
                         label=[nd.array(label, ctx=cpu())],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self

    def close(self):
        if self._epoch_stop is not None:
            self._epoch_stop.set()
        if self._queue is not None:
            # unblock a producer waiting on a full queue so it can exit
            try:
                while True:
                    self._queue.get_nowait()
            except _queue.Empty:
                pass
        if self._producer is not None and self._producer.is_alive():
            self._producer.join(timeout=10)
        # wait for in-flight reads before munmapping the native mapping —
        # a worker mid-read on an unmapped page would SIGSEGV
        self._pool.shutdown(wait=True)
        if self._native is not None:
            self._native.close()
            self._native = None


def _truthy(v):
    if isinstance(v, str):
        return v.lower() in ("1", "true", "yes")
    return bool(int(v)) if isinstance(v, (int, float)) else bool(v)


def _scan_offsets(path):
    """One cheap pass over the .rec collecting record start offsets."""
    offsets = []
    with open(path, "rb") as fp:
        off = 0
        pending = False  # inside a multi-part record
        while True:
            head = fp.read(8)
            if len(head) < 8:
                break
            magic, lrec = struct.unpack("<II", head)
            if magic != 0xCED7230A:
                raise MXNetError("invalid RecordIO magic in %s" % path)
            cflag, length = lrec >> 29, lrec & ((1 << 29) - 1)
            if not pending:
                offsets.append(off)
            pending = cflag == 1 or (pending and cflag == 2)
            skip = length + ((-length) % 4)
            fp.seek(skip, 1)
            off = fp.tell()
    return offsets


def _hsl_jitter(img, rng, rh, rs, rl):
    """Random HSL shift (reference image_aug_default.cc random_h/s/l,
    defaults ImageNet: 36/50/50)."""
    from colorsys import rgb_to_hls, hls_to_rgb  # scalar fallback unused
    # vectorized HSL via numpy
    x = np.clip(img, 0, 255) / 255.0
    maxc = x.max(axis=2)
    minc = x.min(axis=2)
    l = (maxc + minc) / 2.0
    delta = maxc - minc
    s = np.where(delta == 0, 0.0,
                 np.where(l < 0.5, delta / np.maximum(maxc + minc, 1e-8),
                          delta / np.maximum(2.0 - maxc - minc, 1e-8)))
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    dd = np.maximum(delta, 1e-8)
    h = np.where(maxc == r, (g - b) / dd % 6,
                 np.where(maxc == g, (b - r) / dd + 2, (r - g) / dd + 4))
    h = np.where(delta == 0, 0.0, h) * 60.0

    h = (h + rng.uniform(-rh, rh)) % 360.0
    s = np.clip(s + rng.uniform(-rs, rs) / 255.0, 0, 1)
    l = np.clip(l + rng.uniform(-rl, rl) / 255.0, 0, 1)

    c = (1 - np.abs(2 * l - 1)) * s
    hp = h / 60.0
    xv = c * (1 - np.abs(hp % 2 - 1))
    zero = np.zeros_like(c)
    conds = [hp < 1, hp < 2, hp < 3, hp < 4, hp < 5, hp >= 5]
    rgbs = [(c, xv, zero), (xv, c, zero), (zero, c, xv),
            (zero, xv, c), (xv, zero, c), (c, zero, xv)]
    r2 = np.select(conds, [t[0] for t in rgbs])
    g2 = np.select(conds, [t[1] for t in rgbs])
    b2 = np.select(conds, [t[2] for t in rgbs])
    m = l - c / 2.0
    out = np.stack([r2 + m, g2 + m, b2 + m], axis=2)
    return out * 255.0
