"""Distributed KVStore over jax.distributed collectives.

Reference parity: src/kvstore/kvstore_dist.h:44-500 (worker: ZPush/ZPull
to parameter servers over ps-lite/ZMQ) and kvstore_dist_server.h:152-300
(server: per-key aggregation with a sync barrier counting pushes from all
workers; optimizer-on-server via set_optimizer). TPU-native mapping
(SURVEY.md §2.3/§5.8): there are **no server processes** — ps-lite is
replaced by ``jax.distributed`` + XLA collectives (ICI within a slice,
DCN across slices / Gloo on CPU). Each push is a collective all-gather +
sum across workers, which gives the reference's ``dist_sync`` semantics
by construction: every worker's push participates before any pull
observes the value. The "server state" (weights + optimizer state) is
replicated deterministically on every worker — same reduced gradient,
same updater, same result — so pull never needs a wire transfer at all.

``dist_async`` does NOT live here: Hogwild-style async applies make no
sense on a collective transport (collectives are barriers by
construction), so ``mx.kv.create('dist_async')`` dispatches to the real
parameter-server implementation in kvstore_async.py (immediate per-push
applies, free-running workers). ``get_num_dead_node``/``is_recovery``
map to the jax coordination service's own failure model: a dead process
fails the job, so the live view is always "0 dead".

Process topology comes from the launcher (tools/launch.py) via env vars,
reference names honored: DMLC_NUM_WORKER, DMLC_PS_ROOT_URI/PORT, and
MXTPU_WORKER_RANK for the rank (ps-lite assigned ranks dynamically; a
collective world needs them pinned at spawn).
"""
from __future__ import annotations

import os

import numpy as _np

from .base import MXNetError
from .kvstore import KVStore, _key_value, _updater_key
from .ndarray import NDArray

__all__ = ["KVStoreDist"]

_initialized = False


def _ensure_dist():
    """Verify the collective world is up. The actual
    jax.distributed.initialize happens at package import
    (mxnet_tpu._maybe_init_distributed) because it must precede any XLA
    backend touch; by kvstore-creation time the backend is long live."""
    global _initialized
    if _initialized:
        return
    import jax
    n = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if n > 1 and jax.process_count() != n:
        raise MXNetError(
            "dist kvstore: DMLC_NUM_WORKER=%d but jax.process_count()=%d — "
            "the collective world was not initialized at import. Launch "
            "workers via tools/launch.py (it sets DMLC_ROLE=worker and the "
            "coordinator env before Python starts)." % (n, jax.process_count()))
    _initialized = True


class KVStoreDist(KVStore):
    """Multi-process synchronous kvstore (see module docstring)."""

    _captures_local_state = False    # replicated-by-collective, but the
    # legacy persistence contract keeps state behind the kvstore file API

    def __init__(self, name="dist_sync"):
        super().__init__(name)
        if "async" in name:
            raise MXNetError(
                "KVStoreDist is the collective (sync) transport; "
                "'%s' must be created via mx.kv.create, which dispatches "
                "async names to kvstore_async.KVStoreDistAsync" % name)
        # this store overrides push, so the compiled bucketed engine
        # never engages: every step rides the eager per-key loop — say
        # so ONCE (and count it) instead of silently forfeiting the
        # hot path; kvstore='tpu' is the compiled multi-host store
        from .kvstore import _note_fallback
        _note_fallback(
            "legacy_dist_kvstore:%s" % name,
            detail="ps-lite-shaped store, every push is eager per-key; "
                   "use kvstore='tpu' for the compiled collective path")
        _ensure_dist()
        import jax
        self._rank = jax.process_index()
        self._nworkers = jax.process_count()
        self._barrier_count = 0

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._nworkers

    def init(self, key, value):
        """Initialize keys from rank 0's values (reference
        kvstore_dist.h:181-197: only worker 0 pushes init, others
        barrier)."""
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if k in self._store:
                continue
            v = vlist[0]
            if self._nworkers > 1:
                import jax.numpy as jnp
                from jax.experimental import multihost_utils
                arr = multihost_utils.broadcast_one_to_all(v._data)
                self._store[k] = NDArray(jnp.asarray(_np.asarray(arr)),
                                         v.context)
            else:
                self._store[k] = v.copy()

    def _allreduce(self, k, value):
        """Sum a per-worker value across all workers (the ZPush/server-
        aggregate/ZPull round of the reference, as one collective). With
        compression on, the packed 2-bit buffer is what crosses the wire;
        a single-worker world still quantizes (semantics must not depend
        on world size)."""
        if self._compression is not None:
            packed, shape, dtype = self._compress_wire(k, value)
            if self._nworkers == 1:
                return NDArray(
                    self._compression.decompress(packed, shape, dtype),
                    value.context)
            from jax.experimental import multihost_utils
            gathered = multihost_utils.process_allgather(packed)
            total = None
            for w in range(gathered.shape[0]):
                part = self._compression.decompress(gathered[w], shape, dtype)
                total = part if total is None else total + part
            return NDArray(total, value.context)
        if self._nworkers == 1:
            return value.copy()
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(value._data)
        return NDArray(gathered.sum(axis=0), value.context)

    def _compress_wire(self, k, grad):
        """Quantize to the packed 2-bit wire format with per-key error
        feedback (reference gradient_compression-inl.h quantize_2bit;
        the packed uint8 buffer is what crosses DCN)."""
        residual = self._get_residual((k, "wire"), grad)
        packed, new_residual = self._compression.compress(
            grad._data, residual._data)
        residual._set_data(new_residual)
        return packed, grad.shape, grad._data.dtype

    def push(self, key, value, priority=0):
        """Reduce local device list, then all-reduce across workers; with
        an updater set, apply it to the globally reduced value (the
        reference's optimizer-on-server mode, kvstore_dist_server.h:262-300
        ApplyUpdates). Collective: every worker must push every key."""
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            reduced = self._allreduce(k, self._local_reduce(vlist))
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError("key %s not initialized" % k)
                self._updater(_updater_key(k), reduced, self._store[k])
            else:
                self._store[k] = reduced

    def barrier(self):
        """Global barrier across workers (reference ps::Postoffice
        Barrier)."""
        if self._nworkers > 1:
            from jax.experimental import multihost_utils
            self._barrier_count += 1
            multihost_utils.sync_global_devices(
                "mxtpu_kv_barrier_%d" % self._barrier_count)

    def get_num_dead_node(self, node_id=0, timeout=60):
        return 0

    @property
    def is_recovery(self):
        return False
