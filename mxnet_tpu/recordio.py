"""Read/write the RecordIO data format (.rec/.idx) — pure Python.

Reference parity: python/mxnet/recordio.py (MXRecordIO, MXIndexedRecordIO,
IRHeader, pack/unpack, pack_img/unpack_img) over the dmlc-core recordio
wire format (3rdparty/dmlc-core recordio: per-chunk ``[magic u32][lrec
u32][data][pad to 4]`` where ``lrec >> 29`` is the continue-flag and
``lrec & 0x1FFFFFFF`` the chunk length; records larger than 2^29-1 bytes
are split into chunks flagged 1/2/3 = first/middle/last). Files written
here are byte-compatible with the reference's .rec files.

One deliberate divergence: image decode/encode uses PIL, not OpenCV, so
``unpack_img``/``imdecode`` return **RGB** channel order (the reference's
cv2 path returns BGR and flips to RGB later in mx.image). All of
mxnet_tpu handles images as RGB end to end.
"""
from __future__ import annotations

import io as _pyio
import numbers
import os
import struct
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A
_LEN_MASK = (1 << 29) - 1
_MAX_CHUNK = _LEN_MASK


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


class MXRecordIO:
    """Sequential reader/writer for RecordIO files (reference
    recordio.py:36)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self._fp = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self._fp = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        """Override pickling behavior: a reader re-opens at the same
        position in the worker (DataLoader multiprocessing parity)."""
        if self.writable:
            raise RuntimeError("cannot pickle a writable MXRecordIO")
        d = dict(self.__dict__)
        d.pop("_fp", None)
        d["_pos"] = self._fp.tell() if self.is_open else 0
        return d

    def __setstate__(self, d):
        pos = d.pop("_pos", 0)
        self.__dict__.update(d)
        self.open()
        self._fp.seek(pos)

    def close(self):
        if not self.is_open:
            return
        self._fp.close()
        self.is_open = False

    def reset(self):
        """Reset to the first record ('w' truncates the file)."""
        self.close()
        self.open()

    def write(self, buf):
        """Append one record (bytes or str)."""
        assert self.writable
        if isinstance(buf, str):
            buf = buf.encode("utf-8")
        n = len(buf)
        if n <= _MAX_CHUNK:
            chunks = [(0, buf)]
        else:
            chunks = []
            off = 0
            while off < n:
                piece = buf[off:off + _MAX_CHUNK]
                off += len(piece)
                if not chunks:
                    cflag = 1
                elif off >= n:
                    cflag = 3
                else:
                    cflag = 2
                chunks.append((cflag, piece))
        for cflag, piece in chunks:
            self._fp.write(struct.pack("<II", _MAGIC,
                                       _encode_lrec(cflag, len(piece))))
            self._fp.write(piece)
            pad = (-len(piece)) % 4
            if pad:
                self._fp.write(b"\x00" * pad)

    def read(self):
        """Read the next record; returns bytes or None at EOF."""
        assert not self.writable
        parts = []
        while True:
            head = self._fp.read(8)
            if len(head) < 8:
                return b"".join(parts) if parts else None
            magic, lrec = struct.unpack("<II", head)
            if magic != _MAGIC:
                raise IOError("invalid RecordIO magic at offset %d"
                              % (self._fp.tell() - 8))
            cflag, length = lrec >> 29, lrec & _LEN_MASK
            data = self._fp.read(length)
            pad = (-length) % 4
            if pad:
                self._fp.read(pad)
            parts.append(data)
            if cflag in (0, 3):
                return b"".join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with an index file for random access (reference
    recordio.py:160)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        self.fidx = open(self.idx_path, self.flag)
        if not self.writable:
            for line in iter(self.fidx.readline, ""):
                line = line.strip().split("\t")
                key = self.key_type(line[0])
                self.idx[key] = int(line[1])
                self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        super().close()
        if self.fidx is not None:
            self.fidx.close()

    def __getstate__(self):
        d = super().__getstate__()
        d.pop("fidx", None)
        return d

    def seek(self, idx):
        """Position the reader at record ``idx``."""
        assert not self.writable
        self._fp.seek(self.idx[idx])

    def tell(self):
        """Current write position (byte offset of the next record)."""
        assert self.writable
        return self._fp.tell()

    def read_idx(self, idx):
        """Read the record stored under key ``idx``."""
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        """Append a record under key ``idx``."""
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + raw bytes into an MXImageRecord payload
    (reference recordio.py pack; format 'IfQQ' + optional label array)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + (s if isinstance(s, bytes) else s.encode())
    if isinstance(s, str):
        s = s.encode("utf-8")
    return struct.pack(_IR_FORMAT, *header) + s


def unpack(s):
    """Inverse of :func:`pack`; returns (IRHeader, payload bytes)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        header = header._replace(
            label=np.frombuffer(s, np.float32, header.flag))
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=-1):
    """Unpack an MXImageRecord into (header, HWC uint8 ndarray).
    ``iscolor``: 1 forces RGB, 0 forces grayscale, -1 keeps as stored
    (cv2.imdecode flag parity; channel order is RGB, see module doc)."""
    from PIL import Image
    header, s = unpack(s)
    img = Image.open(_pyio.BytesIO(s))
    if iscolor == 1:
        img = img.convert("RGB")
    elif iscolor == 0:
        img = img.convert("L")
    return header, np.asarray(img)


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an HWC uint8 image and pack it (reference pack_img).
    ``quality``: JPEG quality 1-100 or PNG compression 1-9."""
    from PIL import Image
    img = np.asarray(img)
    if img.ndim == 2:
        pil = Image.fromarray(img, mode="L")
    else:
        pil = Image.fromarray(img[:, :, :3].astype(np.uint8), mode="RGB")
    buf = _pyio.BytesIO()
    fmt = img_fmt.lower().lstrip(".")
    if fmt in ("jpg", "jpeg"):
        pil.save(buf, format="JPEG", quality=quality)
    elif fmt == "png":
        pil.save(buf, format="PNG", compress_level=min(quality, 9))
    else:
        raise ValueError("unsupported img_fmt %s" % img_fmt)
    return pack(header, buf.getvalue())
