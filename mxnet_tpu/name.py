"""Name manager (reference python/mxnet/name.py): deterministic auto-name
scopes for symbols. ``with mx.name.NameManager():`` resets the counter
scope so generated names ("fullyconnected0"...) restart — what the
reference's fluent-API tests rely on for reproducible graphs."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]

_TLS = threading.local()


class NameManager:
    """Assigns `hint + running index` names within its scope."""

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return "%s%d" % (hint, idx)

    def __enter__(self):
        self._old = getattr(_TLS, "manager", None)
        _TLS.manager = self
        return self

    def __exit__(self, *exc):
        _TLS.manager = self._old
        return False


class Prefix(NameManager):
    """NameManager that prepends a fixed prefix to every generated name."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current():
    """The innermost active manager (a fresh default if none entered)."""
    mgr = getattr(_TLS, "manager", None)
    if mgr is None:
        mgr = _TLS.manager = NameManager()
    return mgr
