"""NDArray: MXNet's imperative tensor, backed by ``jax.Array``.

Reference parity: include/mxnet/ndarray.h + python/mxnet/ndarray/ndarray.py.
TPU-native mapping (SURVEY.md §7): the reference's dependency-engine variable
per array (src/engine/threaded_engine.h:115) is replaced by JAX's own async
dispatch — ``wait_to_read`` maps to ``block_until_ready``. Storage handles
(src/storage/) are replaced by XLA's HBM allocator; ``Context`` decides the
``jax.Device`` an array is committed to.

Mutability: MXNet NDArrays are mutable buffers. Here mutation rebinds the
wrapped immutable ``jax.Array`` (``_set_data``), and sliced writes lower to
XLA scatter (``.at[]``) — in-place semantics are preserved at the NDArray
level while the compiled world stays functional.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError, integer_types, numeric_types
from ..context import Context, current_context
from . import dispatch as _dispatch

__all__ = ["NDArray", "array", "empty", "zeros", "ones", "full", "arange",
           "concatenate", "waitall", "moveaxis", "onehot_encode", "imm"]


class NDArray:
    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_autograd_entry",
                 "_deferred_init", "__weakref__")

    # make numpy defer to NDArray.__r<op>__
    __array_priority__ = 1000.0

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._data
        self._data = data
        self._ctx = ctx if ctx is not None else current_context()
        self._grad = None
        self._grad_req = "null"
        self._autograd_entry = None

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def grad(self):
        return self._grad

    @property
    def handle(self):  # parity shim: some user code checks identity via handle
        return id(self)

    def __repr__(self):
        return "\n%s\n<NDArray %s @%s>" % (
            _np.asarray(self._data), "x".join(str(s) for s in self.shape), self._ctx)

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple "
                         "elements is ambiguous.")

    def __getattr__(self, name):
        # Fluent surface (reference ndarray.py registers every op as a
        # method): resolve registered op names lazily so x.norm(),
        # x.nansum(axis=...) etc. work without hand-written wrappers.
        if name.startswith("_"):
            raise AttributeError(name)
        from ..ops import registry as _registry
        try:
            _registry.get_op(name)
        except Exception:
            raise AttributeError(
                f"'NDArray' object has no attribute {name!r}") from None

        def _fluent(*args, **kwargs):
            extra = []
            for a in args:
                if isinstance(a, NDArray):
                    extra.append(a)
                else:
                    raise TypeError(
                        f"{name}: positional non-NDArray arguments are "
                        f"not supported on the fluent form; pass keywords")
            return _dispatch.invoke_by_name(name, [self, *extra], kwargs)
        _fluent.__name__ = name
        return _fluent

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------------
    # host/device movement & sync
    # ------------------------------------------------------------------
    def asnumpy(self):
        """Blocking copy to host (the reference's implicit sync point).
        Always WRITABLE like the reference's copy — jax would otherwise
        hand back a read-only zero-copy view on CPU."""
        out = _np.asarray(self._data)
        return out if out.flags.writeable else out.copy()

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def astype(self, dtype, copy=True):
        out = jnp.asarray(self._data, dtype=dtype)
        if not copy and out.dtype == self.dtype:
            return self
        return NDArray(out, self._ctx)

    def copy(self):
        return NDArray(self._data, self._ctx)

    def copyto(self, other):
        """Copy to another NDArray or Context (reference: CopyFromTo,
        src/ndarray/ndarray.cc:1147)."""
        if isinstance(other, NDArray):
            if other is self:
                return other
            other._set_data(jax.device_put(self._data, other._ctx.jax_device))
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device), other)
        raise TypeError("copyto does not support type %s" % type(other))

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    def wait_to_read(self):
        jax.block_until_ready(self._data)

    def wait_to_write(self):
        jax.block_until_ready(self._data)

    # ------------------------------------------------------------------
    # mutation
    # ------------------------------------------------------------------
    def _set_data(self, new_data):
        if tuple(new_data.shape) != self.shape:
            raise MXNetError("in-place assignment shape mismatch %s vs %s"
                             % (tuple(new_data.shape), self.shape))
        if new_data.dtype != self._data.dtype:
            new_data = jnp.asarray(new_data, dtype=self._data.dtype)
        self._data = new_data

    def _sync_copyfrom(self, source):
        arr = _np.asarray(source, dtype=self.dtype)
        if arr.shape != self.shape:
            raise MXNetError("shape mismatch in _sync_copyfrom")
        # keep the buffer's CURRENT placement: an array a bind installed
        # on a GSPMD mesh (replicated runtime inputs of a TP-sharded
        # decode step, mx.fleet) must not collapse back to the single
        # bind device — that would hand jit arguments committed to
        # different device sets.  For ordinary single-device arrays the
        # existing sharding IS the ctx device, so behavior is unchanged.
        self._data = jax.device_put(jnp.asarray(arr), self._data.sharding)

    @staticmethod
    def _norm_key(key):
        """jax rejects bare python sequences as fancy indices; numpy-ify
        them (also unwrap NDArray indices), at any nesting level of a
        tuple key."""
        if isinstance(key, NDArray):
            return key._data
        if isinstance(key, list):
            return _np.asarray(key)
        if isinstance(key, tuple):
            return tuple(NDArray._norm_key(k) if isinstance(k, (list, NDArray))
                         else k for k in key)
        return key

    def __setitem__(self, key, value):
        key = NDArray._norm_key(key)
        from .. import autograd as _ag
        recorded = (self._grad is not None
                    or self._autograd_entry is not None
                    or (isinstance(value, NDArray)
                        and (value._grad is not None
                             or value._autograd_entry is not None)))
        if _ag.is_recording() and recorded:
            # only arrays PARTICIPATING in the recorded graph are
            # protected — scratch buffers (deferred init, metrics) may
            # still be written while a record scope is open elsewhere
            raise MXNetError(
                "Inplace operations (+=, -=, x[:]=, etc) are not supported "
                "when recording with autograd (reference ndarray.py "
                "check_call guard); compute a new array instead")
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, (_np.ndarray, _np.generic, list)):
            value = jnp.asarray(value, dtype=self.dtype)
        if isinstance(key, tuple) and len(key) == 0:
            key = slice(None)
        if key is None or (isinstance(key, slice) and key == slice(None)):
            if isinstance(value, numeric_types):
                self._data = jnp.full(self.shape, value, dtype=self.dtype)
            else:
                v = jnp.broadcast_to(jnp.asarray(value, dtype=self.dtype), self.shape)
                self._data = v
            return
        try:
            self._data = self._data.at[key].set(value)
        except (TypeError, ValueError):
            # reference/numpy assignment semantics: a size-matching value
            # with EXTRA SIZE-1 DIMS squeezes into the slot
            # (b[0] = np.array([47.8]) — apache/incubator-mxnet#8668).
            # Only squeezing is allowed — arbitrary same-size reshapes
            # (e.g. (3,2) into a (2,3) slot) must keep raising.
            slot = jnp.shape(self._data[key])
            v = jnp.asarray(value, dtype=self.dtype)
            squeeze = tuple(d for d in v.shape if d != 1)
            if squeeze == tuple(d for d in slot if d != 1):
                self._data = self._data.at[key].set(v.reshape(slot))
            else:
                raise

    def __getitem__(self, key):
        key = NDArray._norm_key(key)
        from .. import autograd as _ag
        if _ag.is_recording():
            # Keep sliced reads on the tape (indices are captured
            # constants — no gradient flows through them). NOTE: each
            # distinct key compiles + caches its own program; loops that
            # slice with varying indices under record() should prefer
            # nd.take / nd.slice_axis (traced operands) on the hot path.
            return _dispatch.invoke_by_name("_ndarray_getitem", [self],
                                            {"key": key})
        return NDArray(self._data[key], self._ctx)

    # ------------------------------------------------------------------
    # shape ops (view-free: XLA reshapes are free inside jit). Routed
    # through the op dispatch so they land on the autograd tape when
    # recording — a raw jnp call here would silently sever the grad chain.
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if not shape:
            shape = kwargs.get("shape", ())
        if kwargs.get("reverse"):
            # magic values resolve right-to-left (reference matrix_op
            # reverse attr); the op's own inference handles it
            return _dispatch.invoke_by_name(
                "reshape", [self], {"shape": tuple(shape), "reverse": True})
        shape = _infer_reshape(self.shape, tuple(shape))
        return _dispatch.invoke_by_name("reshape", [self], {"shape": shape})

    def reshape_like(self, other=None, rhs=None, **kwargs):
        target = other if other is not None else rhs
        return self.reshape(target.shape)

    def expand_dims(self, axis):
        return _dispatch.invoke_by_name("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return _dispatch.invoke_by_name("squeeze", [self], {"axis": axis})

    def transpose(self, *axes, **kwargs):
        if not axes and kwargs.get("axes") is not None:
            axes = tuple(kwargs["axes"])
        elif len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        axes = axes if axes else None
        return _dispatch.invoke_by_name("transpose", [self], {"axes": axes})

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        return self.reshape((self.shape[0], -1))

    def broadcast_to(self, shape):
        return _dispatch.invoke_by_name("broadcast_to", [self],
                                        {"shape": tuple(shape)})

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def swapaxes(self, dim1, dim2):
        return _dispatch.invoke_by_name("swapaxes", [self],
                                        {"dim1": dim1, "dim2": dim2})

    def tile(self, reps):
        return _dispatch.invoke_by_name("tile", [self], {"reps": reps})

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        """Convert to another storage type (reference ndarray.py tostype:
        dense -> row_sparse/csr runs cast_storage)."""
        if stype == "default":
            return self
        if stype in ("row_sparse", "csr"):
            from . import sparse as _sparse
            return _sparse.cast_storage(self, stype)
        raise MXNetError(f"unknown storage type {stype!r}")

    # ------------------------------------------------------------------
    # autograd hooks (implemented in mxnet_tpu.autograd)
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        from .. import autograd
        autograd.mark_variables([self], [zeros(self.shape, self._ctx, self.dtype)],
                                grad_reqs=grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], out_grads=None if out_grad is None else [out_grad],
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self):
        out = NDArray(self._data, self._ctx)
        return out

    # ------------------------------------------------------------------
    # arithmetic — routed through the op registry so autograd records them
    # ------------------------------------------------------------------
    def _binop(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return _dispatch.invoke_by_name(op, [a, b], {})
        if isinstance(other, numeric_types):
            return _dispatch.invoke_by_name(
                scalar_op, [self], {"scalar": float(other), "reverse": reverse})
        if isinstance(other, _np.ndarray):
            return self._binop(array(other, self._ctx), op, scalar_op, reverse)
        return NotImplemented

    def __add__(self, o): return self._binop(o, "broadcast_add", "_plus_scalar")
    def __radd__(self, o): return self._binop(o, "broadcast_add", "_plus_scalar", True)
    def __sub__(self, o): return self._binop(o, "broadcast_sub", "_minus_scalar")
    def __rsub__(self, o): return self._binop(o, "broadcast_sub", "_minus_scalar", True)
    def __mul__(self, o): return self._binop(o, "broadcast_mul", "_mul_scalar")
    def __rmul__(self, o): return self._binop(o, "broadcast_mul", "_mul_scalar", True)
    def __truediv__(self, o): return self._binop(o, "broadcast_div", "_div_scalar")
    def __rtruediv__(self, o): return self._binop(o, "broadcast_div", "_div_scalar", True)
    def __mod__(self, o): return self._binop(o, "broadcast_mod", "_mod_scalar")
    def __rmod__(self, o): return self._binop(o, "broadcast_mod", "_mod_scalar", True)
    def __pow__(self, o): return self._binop(o, "broadcast_power", "_power_scalar")
    def __rpow__(self, o): return self._binop(o, "broadcast_power", "_power_scalar", True)
    def __neg__(self): return self._binop(-1.0, None, "_mul_scalar")

    def __eq__(self, o):
        if o is None:
            return False
        return self._binop(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o): return self._binop(o, "broadcast_greater", "_greater_scalar")
    def __ge__(self, o): return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")
    def __lt__(self, o): return self._binop(o, "broadcast_lesser", "_lesser_scalar")
    def __le__(self, o): return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __iadd__(self, o):
        out = self.__add__(o)
        self._set_data(out._data)
        return self

    def __isub__(self, o):
        out = self.__sub__(o)
        self._set_data(out._data)
        return self

    def __imul__(self, o):
        out = self.__mul__(o)
        self._set_data(out._data)
        return self

    def __itruediv__(self, o):
        out = self.__truediv__(o)
        self._set_data(out._data)
        return self

    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx": str(self._ctx)}

    def __setstate__(self, state):
        dev, idx = state["ctx"].split("(")
        ctx = Context(dev, int(idx[:-1]))
        self._ctx = ctx
        self._data = jax.device_put(jnp.asarray(state["data"]), ctx.jax_device)
        self._grad = None
        self._grad_req = "null"
        self._autograd_entry = None

    # reductions / misc used pervasively in user code -------------------
    def sum(self, axis=None, keepdims=False):
        return _dispatch.invoke_by_name("sum", [self],
                                        {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return _dispatch.invoke_by_name("mean", [self],
                                        {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return _dispatch.invoke_by_name("max", [self],
                                        {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return _dispatch.invoke_by_name("min", [self],
                                        {"axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return _dispatch.invoke_by_name("argmax", [self],
                                        {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return _dispatch.invoke_by_name("argmin", [self],
                                        {"axis": axis, "keepdims": keepdims})

    def abs(self):
        return _dispatch.invoke_by_name("abs", [self], {})

    def clip(self, a_min, a_max):
        return _dispatch.invoke_by_name("clip", [self],
                                        {"a_min": a_min, "a_max": a_max})

    def slice_axis(self, axis, begin, end):
        return _dispatch.invoke_by_name("slice_axis", [self],
                                        {"axis": axis, "begin": begin, "end": end})

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return _dispatch.invoke_by_name(
            "one_hot", [self],
            {"depth": depth, "on_value": on_value, "off_value": off_value})


def _infer_reshape(cur_shape, shape):
    """Support MXNet reshape magic values 0 (copy dim) and -1 (infer)."""
    out = []
    for i, s in enumerate(shape):
        if s == 0:
            out.append(cur_shape[i])
        else:
            out.append(int(s))
    return tuple(out)


# ----------------------------------------------------------------------
# creation functions
# ----------------------------------------------------------------------
def _ctx_or_default(ctx):
    return ctx if ctx is not None else current_context()


def imm(jarr, ctx=None):
    """Wrap an existing jax array without copy."""
    return NDArray(jarr, _ctx_or_default(ctx))


def array(source_array, ctx=None, dtype=None):
    ctx = _ctx_or_default(ctx)
    if isinstance(source_array, NDArray):
        src = source_array._data
        if dtype is not None:
            src = jnp.asarray(src, dtype=dtype)
        return NDArray(jax.device_put(src, ctx.jax_device), ctx)
    arr = _np.asarray(source_array, dtype=dtype)
    if dtype is None and arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    if dtype is None and arr.dtype == _np.int64:
        arr = arr.astype(_np.int32)
    # device_put the numpy buffer DIRECTLY: jnp.asarray first would
    # commit it to the default device (the accelerator) before copying
    # to ctx — a full round trip for every cpu-context array
    return NDArray(jax.device_put(arr, ctx.jax_device), ctx)


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx, dtype)


def _emit(values, ctx, out):
    """Return a fresh NDArray or write into ``out`` (reference out= on
    the creation ops)."""
    if out is None:
        return NDArray(values, ctx)
    out._set_data(values)
    return out


def zeros(shape, ctx=None, dtype="float32", out=None, **kwargs):
    ctx = _ctx_or_default(ctx)
    if isinstance(shape, integer_types):
        shape = (shape,)
    with jax.default_device(ctx.jax_device):
        return _emit(jnp.zeros(shape, dtype=dtype or "float32"), ctx, out)


def ones(shape, ctx=None, dtype="float32", out=None, **kwargs):
    ctx = _ctx_or_default(ctx)
    if isinstance(shape, integer_types):
        shape = (shape,)
    with jax.default_device(ctx.jax_device):
        return _emit(jnp.ones(shape, dtype=dtype or "float32"), ctx, out)


def full(shape, val, ctx=None, dtype="float32", out=None):
    ctx = _ctx_or_default(ctx)
    if isinstance(shape, integer_types):
        shape = (shape,)
    with jax.default_device(ctx.jax_device):
        return _emit(jnp.full(shape, val, dtype=dtype or "float32"), ctx,
                     out)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    ctx = _ctx_or_default(ctx)
    with jax.default_device(ctx.jax_device):
        out = jnp.arange(start, stop, step, dtype=dtype)
        if repeat > 1:
            out = jnp.repeat(out, repeat)
        return NDArray(out, ctx)


def moveaxis(tensor, source, destination):
    # reference compat: destination == ndim means "after the last axis"
    # (MXNet 1.x accepted it; numpy does not)
    nd_ = tensor._data.ndim
    if isinstance(destination, int) and destination == nd_:
        destination = nd_ - 1
    return NDArray(jnp.moveaxis(tensor._data, source, destination),
                   tensor._ctx)


def concatenate(arrays, axis=0, always_copy=True):
    if not arrays:
        raise ValueError("concatenate needs at least one array")
    out = jnp.concatenate([a._data for a in arrays], axis=axis)
    return NDArray(out, arrays[0]._ctx)


def onehot_encode(indices, out):
    depth = out.shape[1]
    res = jax.nn.one_hot(indices._data.astype("int32"), depth, dtype=out.dtype)
    out._set_data(res)
    return out


def waitall():
    """Reference: Engine WaitForAll — block until all async work completes."""
    (jnp.zeros(()) + 0).block_until_ready()
