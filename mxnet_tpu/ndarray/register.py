"""Generate ``nd.<op>`` wrappers from the registry at import time.

Reference parity: python/mxnet/ndarray/register.py:156 _make_ndarray_function
(code-gen'd ctypes wrappers); here wrappers close over OpDefs directly.
"""
from __future__ import annotations

import functools

from ..ops import registry as _reg
from . import dispatch as _dispatch


def _make_op_func(opdef, name):
    def fn(*args, out=None, name=None, **kwargs):
        return _dispatch.invoke(opdef, args, kwargs, out=out)
    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = opdef.__doc__
    return fn


def populate(namespace_dict):
    for name in _reg.list_ops():
        opdef = _reg.get_op(name)
        namespace_dict[name] = _make_op_func(opdef, name)
