"""Eager operator dispatch (the imperative runtime).

Reference parity: src/imperative/imperative.cc Invoke/InvokeOp +
python/mxnet/_ctypes/ndarray.py:65 _imperative_invoke. TPU-native: each
(op, attrs, is_train) triple gets one ``jax.jit``-compiled callable, cached;
XLA's async dispatch replaces the dependency engine. Autograd taping happens
here (reference: Imperative::RecordOp, src/imperative/imperative.cc:183).
"""
from __future__ import annotations

import jax

from ..base import MXNetError
from ..ops import registry as _reg

_JIT_CACHE = {}

import os as _os
_NAIVE_ENGINE = _os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, slice):
        return ("slice", v.start, v.stop, v.step)
    if hasattr(v, "tobytes") and hasattr(v, "shape"):
        # array-valued attr (fancy-index keys): identity by content
        import numpy as _np_
        a = _np_.asarray(v)
        return ("arr", a.shape, str(a.dtype), a.tobytes())
    return v


def _get_jitted(opdef, attrs, is_train, needs_rng, n_inputs):
    key = (opdef.name, _freeze(tuple(sorted(attrs.items()))), is_train,
           needs_rng, n_inputs)
    fn = _JIT_CACHE.get(key)
    if fn is None:
        if needs_rng:
            def run(rng, *arrs):
                with _reg._OpCtxScope(is_train, rng):
                    return opdef.fn(*arrs, **attrs)
        else:
            def run(*arrs):
                with _reg._OpCtxScope(is_train, None):
                    return opdef.fn(*arrs, **attrs)
        # analyze: ok(retrace) the eager op path compiles once per (op, attrs, shape) key by design; _JIT_CACHE is that registry
        fn = jax.jit(run)
        _JIT_CACHE[key] = fn
    return fn


def _op_needs_rng(opdef):
    return getattr(opdef.fn, "_needs_rng", False)


def invoke(opdef, args, kwargs, out=None, name=None):
    """Run an op eagerly on NDArray inputs; returns NDArray or list."""
    from .ndarray import NDArray

    kw_inputs, attrs = opdef.split_kwargs(kwargs)
    attrs = opdef.normalize_attrs(attrs)

    # assemble positional tensor inputs
    if opdef.variadic:
        inputs = list(args)
        if kw_inputs:
            inputs += opdef.ordered_kw_inputs(kw_inputs, attrs,
                                              n_positional=len(args))
        input_names = [str(i) for i in range(len(inputs))]
    else:
        inputs = list(args)
        if len(inputs) > len(opdef.input_names):
            raise MXNetError("%s takes %d tensor inputs, got %d" %
                             (opdef.name, len(opdef.input_names), len(inputs)))
        for nm in opdef.input_names[len(inputs):]:
            inputs.append(kw_inputs.pop(nm, None))
        if kw_inputs:
            raise MXNetError("%s: unexpected inputs %s" % (opdef.name, list(kw_inputs)))
        input_names = opdef.input_names

    ctx = None
    arrs = []
    for x in inputs:
        if isinstance(x, NDArray):
            if ctx is None:
                ctx = x._ctx
            elif x._ctx != ctx:
                # reference semantics: eager ops require one context
                # (imperative_utils.h CheckAndInferDevice)
                raise MXNetError(
                    "%s: all operands must live on one context, got %s "
                    "and %s — move with copyto()/as_in_context()"
                    % (opdef.name, ctx, x._ctx))
            arrs.append(x._data)
        elif x is None:
            arrs.append(None)
        else:
            import jax.numpy as jnp
            arrs.append(jnp.asarray(x))
    from ..context import current_context
    if ctx is None:
        ctx = current_context()

    from .. import autograd
    is_train = autograd.is_training()
    needs_rng = _op_needs_rng(opdef)

    fn = _get_jitted(opdef, attrs, is_train, needs_rng, len(arrs))
    rng = None
    if needs_rng:
        # inside an enclosing trace (hybridized block, executor graph) the
        # scope installed a traced key — drawing the global concrete key
        # there would bake the randomness into the compiled graph
        if _reg.op_context._rng_key is not None:
            rng = _reg.op_context.next_rng_key()
        else:
            from .. import random as _random
            rng = _random.next_key()

    from .. import profiler as _prof
    if _prof.IMPERATIVE_ON:
        with _prof.scope(opdef.name, "operator"):
            raw = fn(rng, *arrs) if needs_rng else fn(*arrs)
    else:
        raw = fn(rng, *arrs) if needs_rng else fn(*arrs)

    if _NAIVE_ENGINE:
        # MXNET_ENGINE_TYPE=NaiveEngine: the synchronous debug oracle —
        # async device errors surface at the faulting op (read once at
        # import, like the reference's engine-singleton init)
        jax.block_until_ready(raw)

    n_out = opdef.out_count(attrs)
    outs_raw = list(raw) if isinstance(raw, (tuple, list)) else [raw]
    if len(outs_raw) != n_out:
        raise MXNetError("%s returned %d outputs, declared %d" %
                         (opdef.name, len(outs_raw), n_out))

    # write mutated values back into their input NDArrays (aux states,
    # optimizer update ops) — reference FMutateInputs semantics.
    for in_name, out_idx in opdef.mutate_inputs:
        idx = input_names.index(in_name) if in_name in input_names else -1
        if idx >= 0 and isinstance(inputs[idx], NDArray):
            inputs[idx]._set_data(outs_raw[out_idx])

    n_vis = opdef.visible_out_count(attrs)
    outputs = [NDArray(o, ctx) for o in outs_raw[:n_vis]]

    if autograd.is_recording():
        autograd._record_op(opdef, attrs, is_train, rng,
                            [x if isinstance(x, NDArray) else None for x in inputs],
                            outputs)

    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs, outputs):
            dst._set_data(src._data)
        return out
    if n_vis == 1:
        return outputs[0]
    return outputs


def invoke_by_name(name, args, kwargs, out=None):
    return invoke(_reg.get_op(name), args, kwargs, out=out)
