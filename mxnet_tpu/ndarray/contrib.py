"""Eager control flow: foreach / while_loop / cond on NDArrays.

Reference parity: python/mxnet/ndarray/contrib.py — the imperative
twins of symbol/contrib.py. Eager mode runs plain Python loops (each op
dispatches asynchronously anyway); the compiled/fused form is the
symbol version or hybridized blocks.
"""
from __future__ import annotations

from ..base import MXNetError
from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x):
    if isinstance(x, NDArray):
        return [x], True
    return list(x), False


def foreach(body, data, init_states):
    """Iterate ``body`` over axis 0 of ``data``
    (reference ndarray/contrib.py foreach)."""
    from . import stack

    datas, single_data = _as_list(data)
    length = datas[0].shape[0]
    outputs = []
    st = init_states
    for i in range(length):
        sl = [d[i] for d in datas]
        out, st = body(sl[0] if single_data else sl, st)
        outputs.append(out)
    if outputs and isinstance(outputs[0], (list, tuple)):
        stacked = [stack(*[o[j] for o in outputs], axis=0)
                   for j in range(len(outputs[0]))]
    else:
        stacked = stack(*outputs, axis=0)
    return stacked, st


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Run ``func`` while ``cond`` holds (reference ndarray/contrib.py
    while_loop). Outputs are stacked and zero-padded to
    ``max_iterations`` like the symbolic version."""
    from . import stack, zeros

    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    lvars, single_var = _as_list(loop_vars)
    steps = []
    i = 0
    while i < max_iterations and bool(cond(*lvars).asscalar()):
        out, new_vars = func(*lvars)
        outs, single_out = _as_list(out) if out is not None else ([], True)
        steps.append(outs)
        lvars, _ = _as_list(new_vars)
        i += 1
    if steps and steps[0]:
        n_out = len(steps[0])
        stacked = []
        for j in range(n_out):
            cols = [s[j] for s in steps]
            pad = max_iterations - len(cols)
            col = stack(*cols, axis=0)
            if pad:
                z = zeros((pad,) + cols[0].shape, cols[0].context,
                          str(cols[0].dtype))
                from . import concat
                col = concat(col, z, dim=0)
            stacked.append(col)
        out = stacked[0] if single_out else stacked
    else:
        out = []
    return out, (lvars[0] if single_var else lvars)


def cond(pred, then_func, else_func):
    """Branch eagerly on a boolean scalar (reference ndarray/contrib.py
    cond)."""
    if bool(pred.asscalar()):
        return then_func()
    return else_func()


# ----------------------------------------------------------------------
# expose every _contrib_* registry op under its stripped name
# (reference python/mxnet/ndarray/contrib.py is code-generated the same
# way from the _contrib_ prefix)
# ----------------------------------------------------------------------
def _install_contrib_ops():
    from ..ops import registry as _reg
    from .register import _make_op_func
    g = globals()
    for _name in _reg.list_ops():
        if not _name.startswith("_contrib_"):
            continue
        short = _name[len("_contrib_"):]
        if short in g:  # hand-written wrappers (foreach/while_loop/cond) win
            continue
        g[short] = _make_op_func(_reg.get_op(_name), short)


_install_contrib_ops()
