"""Sparse NDArrays: row_sparse and csr storage types.

Reference parity: include/mxnet/ndarray.h:61-66 (kDefaultStorage /
kRowSparseStorage / kCSRStorage), python/mxnet/ndarray/sparse.py, and the
sparse kernels in src/operator/tensor/ (dot-inl.h, cast_storage-inl.h).

TPU-native stance (SURVEY.md §7 "hard parts" #3): XLA has no native
sparse tensors, so the *storage* is real — compressed component arrays
(``data``/``indices``/``indptr``) held on device — while *compute*
picks per-op between targeted sparse kernels (CSR matmul lowers to
gather + segment-sum, which XLA turns into efficient scatter/gather on
the MXU-adjacent VPU) and documented dense fallback (any op without a
sparse rule densifies transparently through the lazy ``_data``
property). stype semantics — what the reference's FInferStorageType
decides — are preserved: add(rsp, rsp)→rsp, scalar*rsp→rsp,
mixed→dense, cast_storage/retain/slice behave like the reference.
Indices are int32 on device (reference: int64) — JAX's default int width;
2^31 rows per array is far beyond any practical vocab.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "csr_matrix", "row_sparse_array", "cast_storage", "zeros",
           "empty", "array", "retain", "dot"]


class BaseSparseNDArray(NDArray):
    """Common base: compressed components + lazy densification."""

    __slots__ = ("_sp_data", "_sp_indices", "_sp_indptr", "_sp_shape",
                 "_dense_cache")

    def __init__(self, data, indices, indptr, shape, ctx=None):
        # deliberately NOT calling NDArray.__init__: _data is a property here
        self._sp_data = data
        self._sp_indices = indices
        self._sp_indptr = indptr
        self._sp_shape = tuple(int(s) for s in shape)
        self._dense_cache = None
        self._ctx = ctx if ctx is not None else current_context()
        self._grad = None
        self._grad_req = "null"
        self._autograd_entry = None

    # -- dense bridge ---------------------------------------------------
    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._to_dense()
        return self._dense_cache

    @_data.setter
    def _data(self, value):
        # writing a dense value into a sparse array re-compresses it
        # (reference CopyFromTo dense→sparse does a cast_storage)
        self._set_from_dense(jnp.asarray(value))

    def _set_data(self, value):
        self._data = value

    @property
    def shape(self):
        return self._sp_shape

    @property
    def dtype(self):
        return _np.dtype(self._sp_data.dtype)

    @property
    def data(self):
        """The non-zero values (reference sparse.py .data)."""
        return NDArray(self._sp_data, self._ctx)

    @property
    def indices(self):
        return NDArray(self._sp_indices, self._ctx)

    def astype(self, dtype, copy=True):
        """stype-preserving cast (reference sparse arrays keep storage)."""
        return self._with_data(self._sp_data.astype(dtype))

    def copy(self):
        return self.tostype(self.stype)

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._set_data(self._data)
            return other
        raise TypeError("copyto expects NDArray or sparse NDArray")

    def wait_to_read(self):
        jax.block_until_ready(self._sp_data)

    def __repr__(self):
        return "\n%s\n<%s %s @%s>" % (
            _np.asarray(self._data), type(self).__name__,
            "x".join(str(s) for s in self.shape), self._ctx)

    # stype-preserving arithmetic (FInferStorageType rules)
    def __mul__(self, other):
        from ..base import numeric_types
        if isinstance(other, numeric_types):
            return self._with_data(self._sp_data * other)
        return NDArray.__mul__(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        from ..base import numeric_types
        if isinstance(other, numeric_types):
            return self._with_data(self._sp_data / other)
        return NDArray.__truediv__(self, other)


class RowSparseNDArray(BaseSparseNDArray):
    """First-dim-sparse array: ``data[k] = dense[indices[k]]`` for the
    stored rows, all other rows zero (reference ndarray.h kRowSparse;
    the storage behind embeddings and their gradients)."""

    def __init__(self, data, indices, shape, ctx=None):
        indices = jnp.asarray(indices, jnp.int32)
        super().__init__(jnp.asarray(data), indices, None, shape, ctx)

    @property
    def stype(self):
        return "row_sparse"

    def _to_dense(self):
        dense = jnp.zeros(self._sp_shape, self._sp_data.dtype)
        if self._sp_data.shape[0] == 0:
            return dense
        # additive scatter: identical to set-semantics for canonical
        # (unique-index) arrays, and SUMS duplicate indices — matching
        # how every reduce/coalesce path treats them (a `.set` here
        # silently kept only the last duplicate's rows)
        return dense.at[self._sp_indices].add(self._sp_data)

    def _set_from_dense(self, dense):
        if tuple(dense.shape) != self._sp_shape:
            raise MXNetError("shape mismatch writing into RowSparseNDArray")
        rsp = _dense_to_rsp(dense)
        self._sp_data, self._sp_indices = rsp
        self._dense_cache = dense

    def _with_data(self, new_data):
        return RowSparseNDArray(new_data, self._sp_indices, self._sp_shape,
                                self._ctx)

    def tostype(self, stype):
        if stype == "row_sparse":
            return RowSparseNDArray(self._sp_data, self._sp_indices,
                                    self._sp_shape, self._ctx)
        if stype == "default":
            return NDArray(self._to_dense(), self._ctx)
        if stype == "csr":
            raise MXNetError("row_sparse -> csr cast is not defined "
                             "(reference cast_storage supports "
                             "default<->rsp and default<->csr)")
        raise MXNetError("unknown stype %s" % stype)

    def retain(self, row_ids):
        """Keep only the given rows (reference sparse_retain op)."""
        rid_host = _np.asarray(
            row_ids._data if isinstance(row_ids, NDArray) else row_ids
        ).astype(_np.int64)
        # membership on host: the components come to host anyway, so one
        # fetch + numpy isin beats a device kernel + three syncs
        idx_host = _np.asarray(self._sp_indices)
        keep = _np.isin(idx_host, rid_host)
        kept_idx = idx_host[keep]
        kept_data = _np.asarray(self._sp_data)[keep]
        return RowSparseNDArray(jnp.asarray(kept_data),
                                jnp.asarray(kept_idx),
                                self._sp_shape, self._ctx)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            if tuple(other._sp_shape) != tuple(self._sp_shape):
                raise MXNetError(
                    "add(rsp, rsp) shape mismatch %s vs %s"
                    % (self._sp_shape, other._sp_shape))
            idx = jnp.concatenate([self._sp_indices, other._sp_indices])
            dat = jnp.concatenate([self._sp_data, other._sp_data])
            return _coalesce_rsp(dat, idx, self._sp_shape, self._ctx)
        return NDArray.__add__(self, other)

    def __sub__(self, other):
        if isinstance(other, RowSparseNDArray):
            return self + (other * -1.0)
        return NDArray.__sub__(self, other)


class CSRNDArray(BaseSparseNDArray):
    """Compressed-sparse-row 2-D array (reference ndarray.h kCSRStorage)."""

    def __init__(self, data, indices, indptr, shape, ctx=None):
        super().__init__(jnp.asarray(data),
                         jnp.asarray(indices, jnp.int32),
                         jnp.asarray(indptr, jnp.int32), shape, ctx)
        if len(self._sp_shape) != 2:
            raise MXNetError("csr arrays are 2-D")

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self):
        return NDArray(self._sp_indptr, self._ctx)

    def _to_dense(self):
        n, m = self._sp_shape
        dense = jnp.zeros((n, m), self._sp_data.dtype)
        if self._sp_data.shape[0] == 0:
            return dense
        row_ids = _csr_row_ids(self._sp_indptr, self._sp_data.shape[0])
        return dense.at[row_ids, self._sp_indices].set(self._sp_data)

    def _set_from_dense(self, dense):
        if tuple(dense.shape) != self._sp_shape:
            raise MXNetError("shape mismatch writing into CSRNDArray")
        self._sp_data, self._sp_indices, self._sp_indptr = \
            _dense_to_csr(dense)
        self._dense_cache = dense

    def _with_data(self, new_data):
        return CSRNDArray(new_data, self._sp_indices, self._sp_indptr,
                          self._sp_shape, self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return CSRNDArray(self._sp_data, self._sp_indices,
                              self._sp_indptr, self._sp_shape, self._ctx)
        if stype == "default":
            return NDArray(self._to_dense(), self._ctx)
        if stype == "row_sparse":
            raise MXNetError("csr -> row_sparse cast is not defined")
        raise MXNetError("unknown stype %s" % stype)

    def __getitem__(self, key):
        """Row slicing keeps csr storage (reference sparse.py
        CSRNDArray.__getitem__)."""
        if isinstance(key, int):
            n = self._sp_shape[0]
            if key < 0:
                key += n
            if not 0 <= key < n:
                raise IndexError("index %d out of bounds for axis 0" % key)
            key = slice(key, key + 1)
        if isinstance(key, slice):
            start, stop, step = key.indices(self._sp_shape[0])
            if step != 1:
                raise MXNetError("csr slicing requires step 1")
            stop = max(stop, start)
            iptr = self._sp_indptr[start:stop + 1]
            lo, hi = int(iptr[0]), int(iptr[-1])
            return CSRNDArray(self._sp_data[lo:hi],
                              self._sp_indices[lo:hi],
                              iptr - lo,
                              (stop - start, self._sp_shape[1]), self._ctx)
        raise MXNetError("csr supports only row slicing")


# ----------------------------------------------------------------------
# conversion helpers (cast_storage-inl.h)
# ----------------------------------------------------------------------
def _csr_row_ids(indptr, nnz):
    counts = jnp.diff(indptr)
    return jnp.repeat(jnp.arange(counts.shape[0]), counts,
                      total_repeat_length=int(nnz))


def _dense_to_rsp(dense):
    host = _np.asarray(dense)
    nz_rows = _np.nonzero(host.reshape(host.shape[0], -1).any(axis=1))[0]
    return (jnp.asarray(host[nz_rows]), jnp.asarray(nz_rows, jnp.int32))


def _dense_to_csr(dense):
    host = _np.asarray(dense)
    rows, cols = _np.nonzero(host)
    data = host[rows, cols]
    indptr = _np.zeros(host.shape[0] + 1, _np.int64)
    _np.add.at(indptr, rows + 1, 1)
    indptr = _np.cumsum(indptr)
    return (jnp.asarray(data), jnp.asarray(cols, jnp.int32),
            jnp.asarray(indptr))


def _coalesce_rsp(data, indices, shape, ctx):
    """Merge duplicate row indices by summing (sorted, like the
    reference's rsp aggregation in kvstore comm)."""
    host_idx = _np.asarray(indices)
    uniq, inv = _np.unique(host_idx, return_inverse=True)
    summed = jax.ops.segment_sum(data, jnp.asarray(inv),
                                 num_segments=len(uniq))
    return RowSparseNDArray(summed, jnp.asarray(uniq, jnp.int32), shape, ctx)


def cast_storage(arr, stype):
    """Cast between storage types (reference op cast_storage)."""
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if stype == "default":
        return NDArray(arr._data, arr.context)
    if stype == "row_sparse":
        data, idx = _dense_to_rsp(arr._data)
        return RowSparseNDArray(data, idx, arr.shape, arr.context)
    if stype == "csr":
        data, indices, indptr = _dense_to_csr(arr._data)
        return CSRNDArray(data, indices, indptr, arr.shape, arr.context)
    raise MXNetError("unknown stype %s" % stype)


def retain(arr, row_ids):
    """sparse_retain op (reference sparse_retain-inl.h)."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    return arr.retain(row_ids)


# ----------------------------------------------------------------------
# creation (reference sparse.py csr_matrix / row_sparse_array / zeros)
# ----------------------------------------------------------------------
def csr_matrix(arg1, shape=None, ctx=None, dtype="float32"):
    """Create a CSRNDArray from (data, indices, indptr), a dense
    array-like, or another sparse array."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = jnp.asarray(_unwrap(data), dtype)
        return CSRNDArray(data, _unwrap(indices), _unwrap(indptr),
                          shape, ctx)
    if isinstance(arg1, CSRNDArray):
        return arg1.tostype("csr")
    dense = jnp.asarray(_unwrap(arg1), dtype)
    return cast_storage(NDArray(dense, ctx), "csr")


def row_sparse_array(arg1, shape=None, ctx=None, dtype="float32"):
    """Create a RowSparseNDArray from (data, indices), a dense
    array-like, or another sparse array."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = jnp.asarray(_unwrap(data), dtype)
        return RowSparseNDArray(data, _unwrap(indices), shape, ctx)
    if isinstance(arg1, RowSparseNDArray):
        return arg1.tostype("row_sparse")
    dense = jnp.asarray(_unwrap(arg1), dtype)
    return cast_storage(NDArray(dense, ctx), "row_sparse")


def zeros(stype, shape, ctx=None, dtype="float32"):
    if isinstance(shape, int):
        shape = (shape,)
    if stype == "row_sparse":
        trailing = tuple(shape[1:])
        return RowSparseNDArray(jnp.zeros((0,) + trailing, dtype),
                                jnp.zeros((0,), jnp.int32), shape, ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), jnp.zeros((0,), jnp.int32),
                          jnp.zeros(shape[0] + 1, jnp.int32), shape, ctx)
    if stype == "default":
        from . import ndarray as _nd
        return _nd.zeros(shape, ctx, dtype)
    raise MXNetError("unknown stype %s" % stype)


def empty(stype, shape, ctx=None, dtype="float32"):
    return zeros(stype, shape, ctx, dtype)


def array(source_array, ctx=None, dtype=None):
    """Sparse-preserving array(): sparse in → same stype out."""
    if isinstance(source_array, BaseSparseNDArray):
        return source_array.copy()
    from . import ndarray as _nd
    return _nd.array(source_array, ctx=ctx, dtype=dtype)


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else _np.asarray(x)


# ----------------------------------------------------------------------
# sparse dot (reference src/operator/tensor/dot-inl.h DotCsrDnsDns /
# DotCsrTDnsDns) — gather + segment-sum, the XLA-friendly formulation
# ----------------------------------------------------------------------
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, BaseSparseNDArray):
        if transpose_b:
            raise MXNetError("dot(csr, dense, transpose_b=True) unsupported "
                             "(matches reference)")
        n, m = lhs.shape
        dense = rhs._data
        nnz = lhs._sp_data.shape[0]
        if nnz == 0:
            out_rows = m if transpose_a else n
            return NDArray(jnp.zeros((out_rows,) + tuple(dense.shape[1:]),
                                     dense.dtype), lhs.context)
        row_ids = _csr_row_ids(lhs._sp_indptr, nnz)
        if transpose_a:
            # out[col[k]] += data[k] * dense[row[k]]
            contrib = lhs._sp_data[:, None] * dense[row_ids]
            out = jax.ops.segment_sum(contrib, lhs._sp_indices,
                                      num_segments=m)
        else:
            # out[row[k]] += data[k] * dense[col[k]]
            contrib = lhs._sp_data[:, None] * dense[lhs._sp_indices]
            out = jax.ops.segment_sum(contrib, row_ids, num_segments=n)
        return NDArray(out, lhs.context)
    if isinstance(lhs, BaseSparseNDArray) or isinstance(rhs, BaseSparseNDArray):
        # documented dense fallback for remaining sparse dot combinations
        from . import ndarray as _nd
        return _nd.dot(NDArray(lhs._data), NDArray(rhs._data),
                       transpose_a=transpose_a, transpose_b=transpose_b)
    from . import ndarray as _nd
    return _nd.dot(lhs, rhs, transpose_a=transpose_a,
                   transpose_b=transpose_b)


# ----------------------------------------------------------------------
# lazy (row-sparse) optimizer updates — only rows present in the gradient
# are touched (reference optimizer_op.cc SGDUpdateRspImpl "lazy update",
# adam_update FComputeEx); XLA lowers the row gather/scatter to efficient
# dynamic-slice updates
# ----------------------------------------------------------------------
def _prep_sparse_grad(grad, rescale_grad, clip_gradient):
    g = grad._sp_data.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return grad._sp_indices, g


def sparse_sgd_update(weight, grad, state, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0):
    """SGD(+momentum) on the gradient's rows only."""
    rows, g = _prep_sparse_grad(grad, rescale_grad, clip_gradient)
    w = weight._data
    wr = w[rows].astype(jnp.float32)
    if wd:
        g = g + wd * wr
    if state is not None:
        m = state._data
        new_mr = momentum * m[rows].astype(jnp.float32) - lr * g
        state._set_data(m.at[rows].set(new_mr.astype(m.dtype)))
        new_wr = wr + new_mr
    else:
        new_wr = wr - lr * g
    weight._set_data(w.at[rows].set(new_wr.astype(w.dtype)))


def sparse_adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                       epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0):
    """Adam on the gradient's rows only (lazy_update=True semantics)."""
    rows, g = _prep_sparse_grad(grad, rescale_grad, clip_gradient)
    w = weight._data
    wr = w[rows].astype(jnp.float32)
    if wd:
        g = g + wd * wr
    m, v = mean._data, var._data
    new_mr = beta1 * m[rows] + (1 - beta1) * g
    new_vr = beta2 * v[rows] + (1 - beta2) * jnp.square(g)
    mean._set_data(m.at[rows].set(new_mr.astype(m.dtype)))
    var._set_data(v.at[rows].set(new_vr.astype(v.dtype)))
    new_wr = wr - lr * new_mr / (jnp.sqrt(new_vr) + epsilon)
    weight._set_data(w.at[rows].set(new_wr.astype(w.dtype)))


def sparse_adagrad_update(weight, grad, state, lr, epsilon=1e-7, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0):
    """AdaGrad on the gradient's rows only (reference
    _sparse_adagrad_update, optimizer_op.cc AdagradUpdateRspRspRspImpl).
    Same formula as the dense adagrad_update restricted to the rows:
    history accumulates the pure gradient, wd decays decoupled."""
    rows, g = _prep_sparse_grad(grad, rescale_grad, clip_gradient)
    w = weight._data
    wr = w[rows].astype(jnp.float32)
    h = state._data
    new_hr = h[rows] + jnp.square(g)
    state._set_data(h.at[rows].set(new_hr.astype(h.dtype)))
    new_wr = wr - lr * (g / jnp.sqrt(new_hr + epsilon) + wd * wr)
    weight._set_data(w.at[rows].set(new_wr.astype(w.dtype)))


def sparse_group_adagrad_update(weight, grad, state, lr, epsilon=1e-5,
                                rescale_grad=1.0, clip_gradient=-1.0):
    """Row-wise AdaGrad on the gradient's rows only (reference
    contrib group_adagrad_op.cc GroupAdagradUpdateRspRspRspImpl): ONE
    history cell per row — ``state`` is (vocab, 1) — and no weight
    decay. The compiled sparse-apply program (embedding/engine.py)
    replays this exact op sequence, so this function is its bit-for-bit
    parity oracle."""
    rows, g = _prep_sparse_grad(grad, rescale_grad, clip_gradient)
    w = weight._data
    wr = w[rows].astype(jnp.float32)
    h = state._data
    new_hr = h[rows] + jnp.mean(jnp.square(g), axis=1, keepdims=True)
    state._set_data(h.at[rows].set(new_hr.astype(h.dtype)))
    new_wr = wr - lr * g / jnp.sqrt(new_hr + epsilon)
    weight._set_data(w.at[rows].set(new_wr.astype(w.dtype)))


def group_adagrad_update(weight, grad, state, lr, epsilon=1e-5,
                         rescale_grad=1.0, clip_gradient=-1.0):
    """Dense GroupAdaGrad (reference _contrib_group_adagrad_update):
    the same row-wise history on every row. 2-D weights only — the
    row-wise reduction is defined over the embedding dim."""
    if len(weight.shape) != 2:
        raise MXNetError("group_adagrad_update expects 2-D weights "
                         "(got %s)" % (weight.shape,))
    g = grad._data.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    h = state._data + jnp.mean(jnp.square(g), axis=1, keepdims=True)
    state._set_data(h)
    w = weight._data.astype(jnp.float32) - lr * g / jnp.sqrt(h + epsilon)
    weight._set_data(w.astype(weight.dtype))
