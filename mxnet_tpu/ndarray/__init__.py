"""NDArray package: imperative tensors + generated op namespace.

Parity surface: python/mxnet/ndarray/ — ``mx.nd.<op>`` for every registered
operator, plus creation/converters. ``mx.nd.random`` mirrors the random
sampling namespace.
"""
from .ndarray import (NDArray, array, empty, zeros, ones, full, arange,
                      concatenate, waitall, moveaxis, onehot_encode, imm)
from . import register as _register
from .. import ops as _ops  # ensure all ops are registered

_register.populate(globals())

from . import contrib
from . import sparse
from .sparse import (BaseSparseNDArray, RowSparseNDArray, CSRNDArray,
                     cast_storage)

# `power` etc. convenience aliases matching mx.nd module functions
power = globals().get("broadcast_power")
equal = globals().get("broadcast_equal")
not_equal = globals().get("broadcast_not_equal")
greater = globals().get("broadcast_greater")
lesser = globals().get("broadcast_lesser")
add = globals().get("broadcast_add")
subtract = globals().get("broadcast_sub")
multiply = globals().get("broadcast_mul")
divide = globals().get("broadcast_div")


class _RandomNS:
    """mx.nd.random namespace (parity: python/mxnet/ndarray/random.py)."""

    @staticmethod
    def uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None, out=None, **kw):
        from . import dispatch
        return dispatch.invoke_by_name(
            "_random_uniform", [],
            {"low": low, "high": high, "shape": _as_shape(shape), "dtype": dtype}, out=out)

    @staticmethod
    def normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None, out=None, **kw):
        from . import dispatch
        return dispatch.invoke_by_name(
            "_random_normal", [],
            {"loc": loc, "scale": scale, "shape": _as_shape(shape), "dtype": dtype}, out=out)

    @staticmethod
    def randint(low, high, shape=(), dtype="int32", ctx=None, out=None, **kw):
        from . import dispatch
        return dispatch.invoke_by_name(
            "_random_randint", [],
            {"low": low, "high": high, "shape": _as_shape(shape), "dtype": dtype}, out=out)

    @staticmethod
    def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
        from . import dispatch
        return dispatch.invoke_by_name(
            "_sample_multinomial", [data],
            {"shape": _as_shape(shape), "get_prob": get_prob, "dtype": dtype})

    @staticmethod
    def shuffle(data, **kw):
        from . import dispatch
        return dispatch.invoke_by_name("_shuffle", [data], {})

    @staticmethod
    def exponential(scale=1.0, shape=(), dtype="float32", ctx=None, out=None, **kw):
        from . import dispatch
        return dispatch.invoke_by_name(
            "_random_exponential", [],
            {"lam": 1.0 / scale, "shape": _as_shape(shape), "dtype": dtype}, out=out)

    @staticmethod
    def gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None, out=None, **kw):
        from . import dispatch
        return dispatch.invoke_by_name(
            "_random_gamma", [],
            {"alpha": alpha, "beta": beta, "shape": _as_shape(shape), "dtype": dtype}, out=out)

    @staticmethod
    def poisson(lam=1.0, shape=(), dtype="float32", ctx=None, out=None, **kw):
        from . import dispatch
        return dispatch.invoke_by_name(
            "_random_poisson", [],
            {"lam": lam, "shape": _as_shape(shape), "dtype": dtype}, out=out)


def _as_shape(s):
    return tuple(s) if isinstance(s, (tuple, list)) else (int(s),)


random = _RandomNS()


def eye(N, M=0, k=0, ctx=None, dtype="float32", out=None, **kw):
    """Positional form (reference nd.eye(N, M, k)); the generated wrapper
    would mistake the scalars for tensor inputs."""
    from . import dispatch
    return dispatch.invoke_by_name(
        "_eye", [], {"N": int(N), "M": int(M), "k": int(k),
                     "dtype": dtype}, out=out)


def clip(data, a_min=None, a_max=None, out=None, **kw):
    """Positional-scalar form (reference nd.clip(data, a_min, a_max));
    the generated wrapper would mistake the bounds for tensor inputs.
    Bounds keep their python type so integer arrays stay integer."""
    if a_min is None or a_max is None:
        raise ValueError("nd.clip requires both a_min and a_max")
    from . import dispatch
    return dispatch.invoke_by_name(
        "clip", [data], {"a_min": a_min, "a_max": a_max}, out=out)


def uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return random.uniform(low, high, shape, dtype, ctx, out, **kw)


def normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return random.normal(loc, scale, shape, dtype, ctx, out, **kw)


def sample_multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
    return random.multinomial(data, shape, get_prob, dtype, **kw)


def load(fname):
    from ..serialization import load_ndarray_file
    return load_ndarray_file(fname)


def load_frombuffer(buf):
    """Deserialize nd.save output from bytes (reference
    MXNDArrayLoadFromBuffer)."""
    from ..serialization import load_ndarray_bytes
    return load_ndarray_bytes(buf)


class _InternalNS:
    """mx.nd._internal — the reference's generated _internal ops that
    user/test code calls directly (a thin dispatch shim)."""

    def __getattr__(self, name):
        from . import dispatch

        def op(*args, out=None, **kwargs):
            tensors = []
            for a in args:
                if isinstance(a, NDArray):
                    tensors.append(a)
                else:
                    raise TypeError(
                        f"_internal.{name}: positional scalars are not "
                        f"supported here; pass them as keywords "
                        f"(got {type(a).__name__})")
            return dispatch.invoke_by_name(name, tensors, kwargs, out=out)
        op.__name__ = name
        return op


_internal = _InternalNS()


def save(fname, data):
    from ..serialization import save_ndarray_file
    save_ndarray_file(fname, data)
