"""Graph fusion pass: BatchNorm → ReLU → Convolution(1×1) → _FusedBNReluConv.

The TPU-native analog of a graph-executor rewrite pass (the reference
runs nnvm passes over the bound graph, graph_executor.cc:905; XLA already
does memory planning and elementwise fusion, but it will not fuse an
elementwise producer into a convolution *input*, so this pass rewrites
the Symbol DAG to hand XLA a primitive that does — ops/fused.py).

Matched pattern (all conditions required):

* ``Convolution`` with 1×1 kernel, stride 1, no padding, no groups,
  ``no_bias=True``, channel-last layout;
* fed by ``Activation(act_type='relu')`` whose output has no other
  consumer;
* fed by ``BatchNorm`` on the channel axis whose primary output has no
  other consumer (and whose mean/var outputs are unused);
* optionally, when the conv's only consumer is an elementwise add, the
  add is folded in as the kernel's residual epilogue
  (``fuse_residual=True``).

Anything unmatched is left untouched, so the pass is always safe to
apply; numerics are identical up to float reassociation (tested in
tests/test_fused_conv.py).
"""
from __future__ import annotations

from ..ops import registry as _reg
from .symbol import Symbol, _Node

__all__ = ["fuse_conv_bn", "count_fused"]


def count_fused(symbol):
    """Number of ``_FusedBNReluConv`` nodes in ``symbol`` — callers use
    this to report whether a rewrite actually fused anything (the pass
    silently no-ops on graphs with no channel-last 1×1 sites, e.g.
    NCHW)."""
    return sum(1 for n in symbol._topo()
               if not n.is_var and n.op.name == "_FusedBNReluConv")

_ADD_OPS = ("broadcast_add", "elemwise_add", "_plus", "_add")


def _conv_matches(node):
    if node.is_var or node.op.name != "Convolution":
        return False
    a = node.attrs
    kernel = tuple(a.get("kernel", ()))
    if any(int(k) != 1 for k in kernel) or not kernel:
        return False
    stride = tuple(a.get("stride", ()) or ())
    if any(int(s) != 1 for s in stride):
        return False
    pad = tuple(a.get("pad", ()) or ())
    if any(int(p) != 0 for p in pad):
        return False
    if int(a.get("num_group", 1)) != 1 or not a.get("no_bias", False):
        return False
    layout = a.get("layout")
    return bool(layout) and str(layout).endswith("C")


def _bn_matches(node, ndim_channel_axis):
    if node.is_var or node.op.name != "BatchNorm":
        return False
    a = node.attrs
    if a.get("use_global_stats", False):
        return False
    return int(a.get("axis", 1)) == ndim_channel_axis


def fuse_conv_bn(symbol, fuse_residual=True):
    """Return a new Symbol with every matched BN→ReLU→Conv1×1 triple
    replaced by one ``_FusedBNReluConv`` node. ``fuse_residual`` also
    folds a following elementwise add into the kernel's epilogue."""
    topo = symbol._topo()

    consumers = {}          # (id(node), out_idx) -> count
    for node in topo:
        for inp, oi in node.inputs:
            consumers[(id(inp), oi)] = consumers.get((id(inp), oi), 0) + 1
    for node, oi in symbol._entries:
        consumers[(id(node), oi)] = consumers.get((id(node), oi), 0) + 1

    fused_op = _reg.get_op("_FusedBNReluConv")

    # conv node id -> (bn_node, act_node, conv_node)
    matches = {}
    for node in topo:
        if not _conv_matches(node):
            continue
        (act, act_oi) = node.inputs[0]
        if act.is_var or act_oi != 0 or act.op.name != "Activation" \
                or act.attrs.get("act_type") != "relu":
            continue
        if consumers.get((id(act), 0), 0) != 1:
            continue
        (bn, bn_oi) = act.inputs[0]
        if bn_oi != 0 or not _bn_matches(bn, len(tuple(
                node.attrs.get("kernel", ()))) + 1):
            continue
        if consumers.get((id(bn), 0), 0) != 1:
            continue
        if any(consumers.get((id(bn), i), 0) for i in range(1, 5)):
            continue
        matches[id(node)] = (bn, act, node)

    if not matches:
        return symbol

    # add node id -> (conv_node, residual_entry, conv_input_position)
    add_folds = {}
    fused_convs_in_adds = set()
    if fuse_residual:
        for node in topo:
            if node.is_var or node.op.name not in _ADD_OPS:
                continue
            for pos in (0, 1):
                src, oi = node.inputs[pos]
                if oi == 0 and id(src) in matches \
                        and consumers.get((id(src), 0), 0) == 1 \
                        and id(src) not in fused_convs_in_adds:
                    add_folds[id(node)] = (src, node.inputs[1 - pos], pos)
                    fused_convs_in_adds.add(id(src))
                    break

    memo = {}

    def _fused_attrs(bn, conv, with_residual):
        a = dict(conv.attrs)
        return {
            "num_filter": int(a["num_filter"]),
            "eps": bn.attrs.get("eps", 1e-3),
            "momentum": bn.attrs.get("momentum", 0.9),
            "fix_gamma": bn.attrs.get("fix_gamma", True),
            "use_global_stats": False,
            "layout": a.get("layout"),
            "with_residual": bool(with_residual),
        }

    def _fused_inputs(bn, conv):
        # BatchNorm inputs: data, gamma, beta, moving_mean, moving_var
        data_e, gamma_e, beta_e, mm_e, mv_e = bn.inputs
        weight_e = conv.inputs[1]
        return [data_e, gamma_e, beta_e, mm_e, mv_e, weight_e]

    def rebuild(node):
        if id(node) in memo:
            return memo[id(node)]
        if node.is_var:
            memo[id(node)] = (node, {})
            return memo[id(node)]

        if id(node) in add_folds:
            conv, res_entry, _pos = add_folds[id(node)]
            bn, act, _ = matches[id(conv)]
            ins = [_entry(e) for e in _fused_inputs(bn, conv)]
            ins.append(_entry(res_entry))
            new = _Node(fused_op, conv.name,
                        _fused_attrs(bn, conv, True), ins,
                        dict(conv.str_attrs))
            memo[id(node)] = (new, {0: 0})
            return memo[id(node)]

        if id(node) in matches and id(node) not in fused_convs_in_adds:
            bn, act, conv = matches[id(node)]
            ins = [_entry(e) for e in _fused_inputs(bn, conv)]
            new = _Node(fused_op, conv.name,
                        _fused_attrs(bn, conv, False), ins,
                        dict(conv.str_attrs))
            memo[id(node)] = (new, {0: 0})
            return memo[id(node)]

        ins = [_entry(e) for e in node.inputs]
        new = _Node(node.op, node.name, dict(node.attrs), ins,
                    dict(node.str_attrs), node.cf_meta)
        memo[id(node)] = (new, {})
        return memo[id(node)]

    def _entry(e):
        node, oi = e
        new, remap = rebuild(node)
        return (new, remap.get(oi, oi))

    return Symbol([_entry(e) for e in symbol._entries])
