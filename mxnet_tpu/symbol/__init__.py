"""Symbol package: declarative graph API (mx.sym.*).

Parity surface: python/mxnet/symbol/ — one generated function per registered
operator that composes Symbols, auto-creating parameter variables named
``{node}_{input}`` exactly like the reference (symbol compose semantics in
python/mxnet/symbol/register.py).
"""
from __future__ import annotations

from ..base import MXNetError, current_name_manager
from ..ops import registry as _reg
from .symbol import (Symbol, Variable, var, Group, load, load_json, AttrScope,
                     _Node, _expand_user_attrs)

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "AttrScope"]


def _entry_of(s):
    if len(s._entries) != 1:
        raise MXNetError("cannot use a multi-output Symbol as an op input "
                         "directly; index it first")
    return s._entries[0]


def _invoke_op(opname, sym_inputs, attrs=None, name=None):
    opdef = _reg.get_op(opname)
    given = list(attrs or {})
    attrs = opdef.normalize_attrs(attrs or {})
    nm = current_name_manager().get(name, opdef.name.replace("_", ""))
    inputs = [_entry_of(s) for s in sym_inputs]
    node = _Node(opdef, nm, attrs, inputs, AttrScope.current_attrs(),
                 given_attrs=given)
    vis = opdef.visible_out_count(attrs)
    return Symbol([(node, i) for i in range(vis)]) if vis > 1 else Symbol([(node, 0)])


def _invoke_scalar(opname, s, scalar, reverse):
    return _invoke_op(opname, [s], {"scalar": scalar, "reverse": reverse})


def _make_sym_func(opdef, fname):
    def fn(*args, name=None, attr=None, **kwargs):
        # user attrs riding the op call (reference register.py creator):
        # lr_mult/wd_mult-style kwargs plus free-form __dunder__ kwargs
        # become str attrs, never op params
        user_kwargs = {}
        for k in list(kwargs):
            if (k in ("lr_mult", "wd_mult", "force_mirroring")
                    and k not in opdef.attr_names) \
                    or (k.startswith("__") and k.endswith("__")):
                user_kwargs[k] = str(kwargs.pop(k))
        kw_inputs, attrs = opdef.split_kwargs(kwargs)
        given = list(attrs)
        attrs = opdef.normalize_attrs(attrs)
        hint = opdef.name.lower().replace("_", "")
        nm = current_name_manager().get(name, hint)

        # merged user attrs: enclosing AttrScope, then attr= dict, then
        # attr-ish kwargs (innermost wins, like the reference)
        str_attrs = AttrScope.current_attrs()
        if attr:
            str_attrs.update({k: str(v) for k, v in attr.items()})
        str_attrs.update(user_kwargs)
        str_attrs = _expand_user_attrs(str_attrs)
        # auto-created parameter variables inherit the dunder user attrs
        # (nnvm compose copies __attr__ entries onto the variables it
        # creates — how conv_weight/conv_bias pick up e.g. __init__)
        var_attr = {k: v for k, v in str_attrs.items()
                    if k.startswith("__") and k.endswith("__")}

        if opdef.variadic:
            inputs = [_entry_of(s) for s in args]
            if kw_inputs:
                inputs += [_entry_of(s) for s in
                           opdef.ordered_kw_inputs(kw_inputs, attrs,
                                                   n_positional=len(args))]
        else:
            unused = (opdef.unused_inputs(attrs)
                      if opdef.unused_inputs is not None else set())
            provided = list(args)
            inputs = []
            for i, in_name in enumerate(opdef.input_names):
                if i < len(provided):
                    s = provided[i]
                elif in_name in kw_inputs:
                    s = kw_inputs[in_name]
                elif in_name in unused:
                    continue
                else:
                    # auto-create the parameter variable (ref: nnvm
                    # compose); it inherits the dunder user attrs plus
                    # the enclosing AttrScope (Variable merges the scope
                    # itself — keeps ctx_group placement working)
                    s = Variable("%s_%s" % (nm, in_name),
                                 attr=var_attr or None)
                inputs.append(_entry_of(s))
        node = _Node(opdef, nm, attrs, inputs, str_attrs,
                     given_attrs=given)
        vis = opdef.visible_out_count(attrs)
        if vis > 1:
            return Symbol([(node, i) for i in range(vis)])
        return Symbol([(node, 0)])

    fn.__name__ = fname
    fn.__qualname__ = fname
    fn.__doc__ = opdef.__doc__
    return fn


for _name in _reg.list_ops():
    globals()[_name] = _make_sym_func(_reg.get_op(_name), _name)

zeros = globals()["_zeros"]
ones = globals()["_ones"]
pow = globals().get("broadcast_power")


class _SymRandom:
    @staticmethod
    def uniform(low=0.0, high=1.0, shape=(), dtype="float32", **kw):
        return _invoke_op("_random_uniform",
                          [], {"low": low, "high": high, "shape": tuple(shape),
                               "dtype": dtype}, name=kw.get("name"))

    @staticmethod
    def normal(loc=0.0, scale=1.0, shape=(), dtype="float32", **kw):
        return _invoke_op("_random_normal",
                          [], {"loc": loc, "scale": scale, "shape": tuple(shape),
                               "dtype": dtype}, name=kw.get("name"))


random = _SymRandom()

from . import contrib  # noqa: E402,F401
