"""Symbolic control flow: foreach / while_loop / cond.

Reference parity: python/mxnet/symbol/contrib.py:37 (foreach), :157
(while_loop), and cond — the reference builds subgraph symbols executed
by dedicated control-flow operators. TPU-native: the body is traced into
a sub-Symbol whose free variables become extra inputs of ONE fused graph
node lowering to ``jax.lax.scan`` / ``lax.cond`` — exactly the
compiler-friendly control flow XLA wants (no Python loop in the compiled
step, gradients ride jax's scan/cond rules).

Graphs containing control-flow nodes execute, differentiate AND
serialize like any other: ``tojson`` emits the reference's nested
"subgraphs" field per node plus a ``cf_meta`` rebuild recipe, and
``load_json`` reconstructs the identical lax.scan/lax.cond lowering
(_rebuild_cf).
"""
from __future__ import annotations

from ..base import MXNetError
from ..ops.registry import OpDef
from .symbol import Symbol, _Node, Variable
from . import current_name_manager

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x):
    if isinstance(x, Symbol):
        return [x], True
    return list(x), False


def _subgraph_eval(entries_sym):
    """Build an evaluator running the sub-DAG on jax values inside the
    enclosing trace (op context/rng of the outer program applies)."""
    topo = entries_sym._topo()
    entries = list(entries_sym._entries)

    def run(env):
        vals = {}
        for node in topo:
            if node.is_var:
                if node.name not in env:
                    raise MXNetError("control-flow subgraph: unbound "
                                     "variable '%s'" % node.name)
                vals[(id(node), 0)] = env[node.name]
                continue
            ins = [vals[(id(i), oi)] for i, oi in node.inputs]
            raw = node.op.fn(*ins, **node.attrs)
            outs = list(raw) if isinstance(raw, (tuple, list)) else [raw]
            for i, v in enumerate(outs):
                vals[(id(node), i)] = v
        return [vals[(id(n), oi)] for n, oi in entries]

    return run


def _free_vars(sub, bound_names):
    names = (sub.list_arguments() + sub.list_auxiliary_states())
    return [n for n in names if n not in bound_names]


def _make_node(opname, fn, n_outputs, input_syms, name_hint, cf_meta=None):
    from .symbol import AttrScope

    opdef = OpDef(opname, fn, num_outputs=n_outputs,
                  num_visible_outputs=n_outputs)
    nm = current_name_manager().get(None, name_hint)
    entries = []
    for s in input_syms:
        if len(s._entries) != 1:
            raise MXNetError("control-flow inputs must be single-output "
                             "symbols")
        entries.append(s._entries[0])
    node = _Node(opdef, nm, {}, entries,
                 str_attrs=AttrScope.current_attrs(), cf_meta=cf_meta)
    return [Symbol([(node, i)]) for i in range(n_outputs)]


# ----------------------------------------------------------------------
# lowering builders — pure functions of (subgraph symbols + meta), so a
# node loaded from JSON rebuilds the exact same lax.scan/cond program
# ----------------------------------------------------------------------
def _foreach_lowering(sub, meta):
    import jax

    run = _subgraph_eval(sub)
    data_names = meta["data_names"]
    state_names = meta["state_names"]
    params = meta["params"]
    n_out, n_state = meta["n_out"], meta["n_state"]
    n_data = len(data_names)

    def fn(*inputs):
        xs = inputs[:n_data]
        carry0 = tuple(inputs[n_data:n_data + n_state])
        pvals = dict(zip(params, inputs[n_data + n_state:]))

        def step(carry, x_slices):
            env = dict(zip(data_names, x_slices))
            env.update(zip(state_names, carry))
            env.update(pvals)
            vals = run(env)
            return tuple(vals[n_out:]), tuple(vals[:n_out])

        final, ys = jax.lax.scan(step, carry0, tuple(xs))
        return tuple(ys) + tuple(final)

    return fn


def _while_lowering(sub, meta):
    import jax
    import jax.numpy as jnp

    run = _subgraph_eval(sub)
    var_names = meta["var_names"]
    params = meta["params"]
    n_out, n_var = meta["n_out"], meta["n_var"]
    max_iterations = meta["max_iterations"]

    def fn(*inputs):
        vars0 = tuple(inputs[:n_var])
        pvals = dict(zip(params, inputs[n_var:]))

        def body_all(vars_):
            env = dict(zip(var_names, vars_))
            env.update(pvals)
            vals = run(env)
            pred = jnp.squeeze(vals[0]).astype(bool)
            return pred, tuple(vals[1:1 + n_out]), tuple(vals[1 + n_out:])

        def step(carry, _):
            alive, vars_ = carry
            pred, outs, nvars = body_all(vars_)
            take = jnp.logical_and(alive, pred)
            new_vars = tuple(jnp.where(take, nv, v)
                             for nv, v in zip(nvars, vars_))
            outs = tuple(jnp.where(take, o, jnp.zeros_like(o))
                         for o in outs)
            return (take, new_vars), outs

        (alive, final_vars), ys = jax.lax.scan(
            step, (jnp.asarray(True), vars0), None, length=max_iterations)
        return tuple(ys) + tuple(final_vars)

    return fn


def _cond_lowering(t_sub, e_sub, meta):
    import jax
    import jax.numpy as jnp

    t_run = _subgraph_eval(t_sub)
    e_run = _subgraph_eval(e_sub)
    t_params, e_params = meta["t_params"], meta["e_params"]
    all_params = meta["all_params"]

    def fn(pred_v, *inputs):
        pvals = dict(zip(all_params, inputs))

        def t_branch(_):
            return tuple(t_run({n: pvals[n] for n in t_params}))

        def e_branch(_):
            return tuple(e_run({n: pvals[n] for n in e_params}))

        p = jnp.squeeze(pred_v).astype(bool)
        return jax.lax.cond(p, t_branch, e_branch, operand=None)

    return fn


def _rebuild_cf(opname, meta):
    """Rebuild (OpDef, n_outputs) for a control-flow node loaded from
    JSON (symbol._load_graph_dict)."""
    subs = meta["subgraphs"]
    if opname == "_foreach":
        n = meta["n_out"] + meta["n_state"]
        fn = _foreach_lowering(subs[0], meta)
    elif opname == "_while_loop":
        n = meta["n_out"] + meta["n_var"]
        fn = _while_lowering(subs[0], meta)
    elif opname == "_cond":
        n = meta["n_out"]
        fn = _cond_lowering(subs[0], subs[1], meta)
    else:
        raise MXNetError("unknown control-flow op '%s'" % opname)
    return OpDef(opname, fn, num_outputs=n, num_visible_outputs=n), n


def foreach(body, data, init_states, name="foreach"):
    """Scan ``body`` over axis 0 of ``data`` (reference
    symbol/contrib.py:37). ``body(data_slice, states) -> (outputs,
    states)``. Lowers to one ``jax.lax.scan``."""
    import jax

    datas, single_data = _as_list(data)
    states, single_state = _as_list(init_states)

    data_vars = [Variable("%s_data%d" % (name, i))
                 for i in range(len(datas))]
    state_vars = [Variable("%s_state%d" % (name, i))
                  for i in range(len(states))]
    outs, out_states = body(data_vars[0] if single_data else data_vars,
                            state_vars[0] if single_state else state_vars)
    out_syms, single_out = _as_list(outs)
    ostate_syms, _ = _as_list(out_states)
    if len(ostate_syms) != len(states):
        raise MXNetError("foreach body must return as many states as "
                         "init_states")

    sub = Symbol([e for s in (out_syms + ostate_syms) for e in s._entries])
    data_names = [v.name for v in data_vars]
    state_names = [v.name for v in state_vars]
    params = _free_vars(sub, set(data_names + state_names))
    n_out, n_state = len(out_syms), len(ostate_syms)

    meta = {"subgraphs": [sub], "data_names": data_names,
            "state_names": state_names, "params": params,
            "n_out": n_out, "n_state": n_state}
    fn = _foreach_lowering(sub, meta)
    out_all = _make_node("_foreach", fn, n_out + n_state,
                         datas + states + list(map(Variable, params)), name,
                         cf_meta=meta)
    outputs = out_all[:n_out]
    fstates = out_all[n_out:]
    return (outputs[0] if single_out else outputs,
            fstates[0] if single_state else fstates)


def while_loop(cond, func, loop_vars, max_iterations=None,
               name="while_loop"):
    """Run ``func`` while ``cond`` holds, at most ``max_iterations``
    times (reference symbol/contrib.py:157). Step outputs are stacked
    into a (max_iterations, ...) array, zero-padded past the actual
    iteration count; returns (outputs, final_loop_vars). Lowers to
    ``jax.lax.scan`` with a live-flag (the XLA-friendly bounded loop)."""
    import jax
    import jax.numpy as jnp

    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    lvars, single_var = _as_list(loop_vars)

    var_vars = [Variable("%s_var%d" % (name, i)) for i in range(len(lvars))]
    cond_sym = cond(*var_vars)
    step_out, new_vars = func(*var_vars)
    out_syms, single_out = _as_list(step_out) if step_out is not None \
        else ([], True)
    nvar_syms, _ = _as_list(new_vars)
    if len(nvar_syms) != len(lvars):
        raise MXNetError("while_loop func must return as many loop_vars")

    sub = Symbol([e for s in ([cond_sym] + out_syms + nvar_syms)
                  for e in s._entries])
    var_names = [v.name for v in var_vars]
    params = _free_vars(sub, set(var_names))
    n_out, n_var = len(out_syms), len(nvar_syms)

    meta = {"subgraphs": [sub], "var_names": var_names, "params": params,
            "n_out": n_out, "n_var": n_var,
            "max_iterations": int(max_iterations)}
    fn = _while_lowering(sub, meta)
    out_all = _make_node("_while_loop", fn, n_out + n_var,
                         lvars + list(map(Variable, params)), name,
                         cf_meta=meta)
    outputs = out_all[:n_out]
    fvars = out_all[n_out:]
    return (outputs[0] if single_out and outputs else outputs,
            fvars[0] if single_var else fvars)


def cond(pred, then_func, else_func, name="cond"):
    """Branch on a scalar symbol (reference symbol/contrib.py cond).
    ``then_func``/``else_func`` are nullary callables returning symbols
    of identical shapes. Lowers to ``jax.lax.cond``."""
    import jax
    import jax.numpy as jnp

    then_out = then_func()
    else_out = else_func()
    t_syms, single = _as_list(then_out)
    e_syms, _ = _as_list(else_out)
    if len(t_syms) != len(e_syms):
        raise MXNetError("cond branches must return the same number of "
                         "outputs")
    n_out = len(t_syms)

    t_sub = Symbol([e for s in t_syms for e in s._entries])
    e_sub = Symbol([e for s in e_syms for e in s._entries])
    t_params = _free_vars(t_sub, set())
    e_params = _free_vars(e_sub, set())
    all_params = list(dict.fromkeys(t_params + e_params))

    meta = {"subgraphs": [t_sub, e_sub], "t_params": t_params,
            "e_params": e_params, "all_params": all_params,
            "n_out": n_out}
    fn = _cond_lowering(t_sub, e_sub, meta)
    out_all = _make_node("_cond", fn, n_out,
                         [pred] + list(map(Variable, all_params)), name,
                         cf_meta=meta)
    return out_all[0] if single else out_all


# ----------------------------------------------------------------------
# expose every _contrib_* registry op under its stripped name
# (reference python/mxnet/symbol/contrib.py is code-generated the same
# way from the _contrib_ prefix)
# ----------------------------------------------------------------------
def _install_contrib_ops():
    from ..ops import registry as _reg
    from . import _make_sym_func
    g = globals()
    for _name in _reg.list_ops():
        if not _name.startswith("_contrib_"):
            continue
        short = _name[len("_contrib_"):]
        if short in g:  # hand-written wrappers (foreach/while_loop/cond) win
            continue
        g[short] = _make_sym_func(_reg.get_op(_name), short)


_install_contrib_ops()
