"""Symbol: the declarative graph IR.

Reference parity: python/mxnet/symbol/symbol.py over nnvm::Symbol. Here the
graph is a plain Python DAG whose nodes reference OpDefs; "compilation" is
tracing the DAG into one XLA computation (executor.py), replacing the
reference's nnvm pass pipeline (Gradient/PlaceDevice/PlanMemory — all
subsumed by jax.grad/sharding/XLA). JSON serialization keeps the reference's
``symbol.json`` node format for checkpoint interop (save_checkpoint writes
the same {"nodes": [...], "arg_nodes": ..., "heads": ...} structure).
"""
from __future__ import annotations

import json
import threading

import numpy as _np

from ..base import MXNetError, current_name_manager
from ..ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "AttrScope"]


class AttrScope:
    """with AttrScope(ctx_group='dev1'): — attach attrs to created nodes
    (reference: python/mxnet/attribute.py; used for model parallelism)."""
    _tls = threading.local()

    def __init__(self, **attrs):
        self._attrs = {k: str(v) for k, v in attrs.items()}

    @classmethod
    def current_attrs(cls):
        stack = getattr(cls._tls, "stack", None)
        merged = {}
        if stack:
            for scope in stack:
                merged.update(scope._attrs)
        return merged

    def __enter__(self):
        if not hasattr(AttrScope._tls, "stack"):
            AttrScope._tls.stack = []
        AttrScope._tls.stack.append(self)
        return self

    def __exit__(self, *a):
        AttrScope._tls.stack.pop()


class _Node:
    __slots__ = ("op", "name", "attrs", "str_attrs", "inputs", "cf_meta",
                 "given_attrs")
    _uid = [0]

    def __init__(self, op, name, attrs, inputs, str_attrs=None,
                 cf_meta=None, given_attrs=None):
        self.op = op            # OpDef or None for variables
        self.name = name
        self.attrs = attrs      # typed op attrs
        self.str_attrs = dict(str_attrs or {})  # user attrs (ctx_group, __shape__…)
        # attr names the CALLER passed (normalize_attrs fills defaults
        # into `attrs`, losing explicitness); None = unknown, fall back
        # to the value-differs-from-default heuristic
        self.given_attrs = (frozenset(given_attrs)
                            if given_attrs is not None else None)
        self.inputs = inputs    # list[(Node, out_idx)]
        # control-flow metadata: {"kind", "subgraphs": [Symbol, ...],
        # **json-able fields} — lets foreach/while_loop/cond nodes
        # serialize (tojson emits the reference's nested "subgraphs"
        # field; load_json rebuilds the lax.scan/cond lowering)
        self.cf_meta = cf_meta

    @property
    def is_var(self):
        return self.op is None

    def out_count(self):
        return 1 if self.is_var else self.op.out_count(self.attrs)

    def visible_out_count(self):
        return 1 if self.is_var else self.op.visible_out_count(self.attrs)

    def output_name(self, idx):
        if self.is_var:
            return self.name
        n = self.visible_out_count()
        if n == 1:
            return self.name + "_output"
        # match reference multi-output naming: name + suffix per output
        return "%s_output%d" % (self.name, idx)

    def explicit_attrs(self):
        """The op attrs the caller actually passed, as {name: value} —
        exact when tracked at creation, else the params whose value
        differs from the registry default (a value explicitly set TO its
        default is indistinguishable then)."""
        if self.is_var:
            return {}
        if self.given_attrs is not None:
            return {k: v for k, v in self.attrs.items()
                    if k in self.given_attrs}
        defaults = self.op.attr_defaults
        return {k: v for k, v in self.attrs.items()
                if k not in defaults or defaults[k] != v}


class Symbol:
    def __init__(self, entries):
        self._entries = list(entries)  # list[(Node, out_idx)]

    # ------------------------------------------------------------------
    # graph traversal
    # ------------------------------------------------------------------
    def _topo(self):
        """Post-order DFS (matches reference nnvm ordering for
        list_arguments)."""
        seen = {}
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for node, _ in self._entries:
            visit(node)
        return order

    def _aux_names_set(self):
        aux = set()
        for node in self._topo():
            if node.is_var or not node.op.mutate_inputs:
                continue
            mut = {nm for nm, _ in node.op.mutate_inputs}
            in_names = node.op.input_names
            for (inp, _), nm in zip(node.inputs, in_names):
                if nm in mut and inp.is_var:
                    aux.add(inp.name)
        return aux

    def list_arguments(self):
        aux = self._aux_names_set()
        out, seen = [], set()
        for node in self._topo():
            if node.is_var and node.name not in aux and node.name not in seen:
                seen.add(node.name)
                out.append(node.name)
        return out

    def list_auxiliary_states(self):
        aux = self._aux_names_set()
        out, seen = [], set()
        for node in self._topo():
            if node.is_var and node.name in aux and node.name not in seen:
                seen.add(node.name)
                out.append(node.name)
        return out

    def list_outputs(self):
        return [node.output_name(idx) for node, idx in self._entries]

    def list_inputs(self):
        return self.list_arguments() + self.list_auxiliary_states()

    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    # ------------------------------------------------------------------
    # composition / indexing
    # ------------------------------------------------------------------
    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("output '%s' not found; outputs=%s" % (index, names))
            index = names.index(index)
        if isinstance(index, slice):
            return Symbol(self._entries[index])
        return Symbol([self._entries[index]])

    def __len__(self):
        return len(self._entries)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def get_internals(self):
        entries = []
        for node in self._topo():
            for i in range(node.visible_out_count()):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self):
        node = self._entries[0][0]
        if not node.inputs:
            return None
        return Symbol([(n, i) for n, i in node.inputs])

    # ------------------------------------------------------------------
    # attrs
    # ------------------------------------------------------------------
    def attr(self, key):
        """User attribute lookup with the reference's dunder fallback:
        ``attr('lr_mult')`` finds a value stored as ``__lr_mult__`` and
        vice versa (conformance: the reference's test_attr reads both
        spellings of the same attribute)."""
        node = self._entries[0][0]
        if key in node.str_attrs:
            return node.str_attrs[key]
        if not (key.startswith("__") and key.endswith("__")):
            return node.str_attrs.get("__%s__" % key)
        stripped = key[2:-2]
        if stripped:
            return node.str_attrs.get(stripped)
        return None

    def list_attr(self, recursive=False):
        """Shallow user-attr dict of the head node (reference
        symbol.py list_attr; recursive aggregation moved to
        ``attr_dict`` in the reference too)."""
        if recursive:
            raise DeprecationWarning(
                "Symbol.list_attr with recursive=True has been deprecated; "
                "use attr_dict instead")
        return dict(self._entries[0][0].str_attrs)

    def attr_dict(self):
        """{node name: attrs} over the whole graph. Matches the
        reference's aggregation: user attrs verbatim, plus — for op
        nodes — the *explicitly given* op params as MXNet-style strings
        (the reference's nnvm attrs.dict holds only what the caller
        passed; filled-in defaults stay out)."""
        out = {}
        for node in self._topo():
            if node.str_attrs or not node.is_var:
                d = dict(node.str_attrs)
                d.update({k: _attr_to_str(v)
                          for k, v in node.explicit_attrs().items()})
                if d:
                    out[node.name] = d
        return out

    def _set_attr(self, **kwargs):
        self._entries[0][0].str_attrs.update(
            _expand_user_attrs({k: str(v) for k, v in kwargs.items()}))

    # ------------------------------------------------------------------
    # shape/type inference
    # ------------------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes = self.infer_shape_partial(*args, **kwargs)
        if arg_shapes is not None and any(s is None for s in arg_shapes):
            missing = [n for n, s in zip(self.list_arguments(), arg_shapes) if s is None]
            raise MXNetError("infer_shape: cannot determine shapes of %s" % missing)
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        known = {}
        arg_names = self.list_arguments()
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    known[n] = tuple(s)
        for k, v in kwargs.items():
            if v is not None:
                known[k] = tuple(v)
        shapes, _ = self._infer(known, {})
        if shapes is None:
            return None, None, None
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in self.list_auxiliary_states()]
        out_shapes = [shapes.get(("out", id(node), idx))
                      for node, idx in self._entries]
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_type(self, shape_kwargs, type_kwargs=None):
        """Joint shape+dtype inference — needed because dtype propagation
        (bf16 data ⇒ bf16 weights) rides the same eval_shape pass. Returns
        (arg_shapes, arg_types, aux_shapes, aux_types)."""
        known_shapes = {k: tuple(v) for k, v in shape_kwargs.items()
                        if v is not None}
        known_dtypes = {k: _np.dtype(v) for k, v in (type_kwargs or {}).items()}
        shapes, dtypes = self._infer(known_shapes, known_dtypes)
        args = self.list_arguments()
        auxs = self.list_auxiliary_states()
        f32 = _np.dtype("float32")
        return ([shapes.get(n) for n in args],
                [dtypes.get(n, f32) for n in args],
                [shapes.get(n) for n in auxs],
                [dtypes.get(n, f32) for n in auxs])

    def infer_type(self, *args, **kwargs):
        known = {}
        arg_names = self.list_arguments()
        if args:
            for n, t in zip(arg_names, args):
                if t is not None:
                    known[n] = _np.dtype(t)
        for k, v in kwargs.items():
            known[k] = _np.dtype(v)
        _, dtypes = self._infer({}, known)
        if dtypes is None:
            return None, None, None
        arg_types = [dtypes.get(n, _np.dtype("float32")) for n in arg_names]
        aux_types = [dtypes.get(n, _np.dtype("float32"))
                     for n in self.list_auxiliary_states()]
        out_types = [dtypes.get(("out", id(node), idx), _np.dtype("float32"))
                     for node, idx in self._entries]
        return arg_types, out_types, aux_types

    def _infer(self, known_shapes, known_dtypes):
        """Forward propagation of shapes+dtypes through the DAG using
        jax.eval_shape per node, with backward param rules filling in
        variable shapes (ops/shape_rules.py)."""
        import jax

        shapes = dict(known_shapes)
        dtypes = dict(known_dtypes)
        env = {}  # (id(node), out_idx) -> jax.ShapeDtypeStruct | None
        # vars whose dtype wasn't given: provisionally fp32, upgraded to the
        # dtype of a sibling input on first use (the reference's same-type
        # FInferType default, e.g. bf16 data ⇒ bf16 conv weights)
        pending_dtype_vars = {}

        for node in self._topo():
            if node.is_var:
                shp = shapes.get(node.name)
                if shp is None and "__shape__" in node.str_attrs:
                    shp = _reg._parse_attr_string(node.str_attrs["__shape__"], None)
                    shapes[node.name] = tuple(shp)
                dt = dtypes.get(node.name)
                if dt is None and "__dtype__" in node.str_attrs:
                    dt = _np.dtype(node.str_attrs["__dtype__"])
                if dt is None:
                    pending_dtype_vars[id(node)] = node
                env[(id(node), 0)] = (
                    jax.ShapeDtypeStruct(tuple(shp), dt or _np.dtype("float32"))
                    if shp is not None else None)
                continue
            # same-dtype rule: resolve pending param-var dtypes from the
            # first input whose dtype is definitively known. Integer inputs
            # (Embedding/take indices, labels) never anchor — the reference's
            # FInferType same-type rule is a float-dtype rule; Embedding
            # weights take their dtype from the op's dtype attr, not the
            # index input.
            anchor = None
            for inp, oi in node.inputs:
                if not (inp.is_var and id(inp) in pending_dtype_vars):
                    sds = env.get((id(inp), oi))
                    if sds is not None and jax.numpy.issubdtype(
                            sds.dtype, _np.floating):  # bf16-aware check
                        anchor = sds.dtype
                        break
            if anchor is not None:
                for inp, oi in node.inputs:
                    if inp.is_var and id(inp) in pending_dtype_vars:
                        sds = env.get((id(inp), 0))
                        if sds is not None:
                            env[(id(inp), 0)] = jax.ShapeDtypeStruct(
                                sds.shape, anchor)
                        dtypes[inp.name] = _np.dtype(anchor)
                        del pending_dtype_vars[id(inp)]

            in_names = (node.op.input_names if not node.op.variadic
                        else [str(i) for i in range(len(node.inputs))])
            known_in = {}
            for (inp, oi), nm in zip(node.inputs, in_names):
                sds = env.get((id(inp), oi))
                known_in[nm] = tuple(sds.shape) if sds is not None else None
            # fill parameter-var shapes via backward rule
            if node.op.param_shapes is not None and any(
                    v is None for v in known_in.values()):
                inferred = node.op.param_shapes(known_in, node.attrs)
                for (inp, oi), nm in zip(node.inputs, in_names):
                    if known_in[nm] is None and nm in inferred and inp.is_var:
                        shp = tuple(inferred[nm])
                        prev = shapes.get(inp.name)
                        if prev is not None and tuple(prev) != shp:
                            raise MXNetError(
                                "shape mismatch for %s: %s vs %s"
                                % (inp.name, prev, shp))
                        shapes[inp.name] = shp
                        dt = dtypes.get(inp.name, _np.dtype("float32"))
                        env[(id(inp), oi)] = jax.ShapeDtypeStruct(shp, dt)
                        known_in[nm] = shp
            ins = [env.get((id(inp), oi)) for inp, oi in node.inputs]
            if any(x is None for x in ins):
                for i in range(node.out_count()):
                    env[(id(node), i)] = None
                continue
            with _reg._OpCtxScope(True, None):
                try:
                    out = jax.eval_shape(
                        lambda *xs: node.op.fn(*xs, **node.attrs), *ins)
                except Exception as e:  # surface the node for debuggability
                    raise MXNetError("shape inference failed at node %s(%s): %s"
                                     % (node.op.name, node.name, e)) from e
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for i, sds in enumerate(outs):
                env[(id(node), i)] = sds

        for node, idx in self._entries:
            sds = env.get((id(node), idx))
            if sds is not None:
                shapes[("out", id(node), idx)] = tuple(sds.shape)
                dtypes[("out", id(node), idx)] = _np.dtype(sds.dtype)
        # record dtypes for vars
        for node in self._topo():
            if node.is_var:
                sds = env.get((id(node), 0))
                if sds is not None:
                    dtypes.setdefault(node.name, _np.dtype(sds.dtype))
        return shapes, dtypes

    # ------------------------------------------------------------------
    # serialization — reference symbol.json format
    # ------------------------------------------------------------------
    def tojson(self):
        nodes = self._topo()
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        arg_nodes = []
        for i, n in enumerate(nodes):
            if n.is_var:
                arg_nodes.append(i)
            # explicit params only — the reference's symbol.json carries
            # what the caller passed, never parser-filled defaults (and
            # load_json can then recover the explicit set exactly)
            attrs = {k: _attr_to_str(v)
                     for k, v in n.explicit_attrs().items()}
            attrs.update(n.str_attrs)
            jn = {"op": "null" if n.is_var else n.op.name,
                  "name": n.name,
                  "inputs": [[nid[id(inp)], oi, 0] for inp, oi in n.inputs]}
            if n.cf_meta is not None:
                # control-flow node: nested graphs ride the reference's
                # "subgraphs" field; the rebuild recipe rides one JSON
                # attr (merged with user attrs like ctx_group)
                meta = dict(n.cf_meta)
                subs = meta.pop("subgraphs")
                jn["subgraphs"] = [json.loads(s.tojson()) for s in subs]
                attrs = dict(n.str_attrs)
                attrs["cf_meta"] = json.dumps(meta)
            if attrs:
                jn["attrs"] = attrs
            jnodes.append(jn)
        heads = [[nid[id(n)], oi, 0] for n, oi in self._entries]
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "heads": heads,
                           "attrs": {"mxnet_version": ["str", "tpu-native-0.1"]}},
                          indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------------------
    # binding/eval — implemented in executor.py
    # ------------------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import Executor
        return Executor._simple_bind(self, ctx, grad_req, type_dict,
                                     group2ctx, shared_exec, shared_buffer,
                                     kwargs)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        return Executor._bind(self, ctx, args, args_grad, grad_req,
                              aux_states, group2ctx, shared_exec)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def __call__(self, *args, **kwargs):
        # composition: replace variable nodes with given symbols
        return self._compose(*args, **kwargs)

    def _compose(self, *args, **kwargs):
        if args and kwargs:
            raise MXNetError("compose accepts positional or keyword, not both")
        arg_names = self.list_arguments()
        mapping = dict(zip(arg_names, args)) if args else dict(kwargs)
        memo = {}

        def rebuild(node):
            if id(node) in memo:
                return memo[id(node)]
            if node.is_var and node.name in mapping:
                new = mapping[node.name]._entries[0][0]
            elif node.is_var:
                new = node
            else:
                new = _Node(node.op, node.name, dict(node.attrs),
                            [(rebuild(i), oi) for i, oi in node.inputs],
                            node.str_attrs, given_attrs=node.given_attrs)
            memo[id(node)] = new
            return new

        return Symbol([(rebuild(n), oi) for n, oi in self._entries])

    # ------------------------------------------------------------------
    # operators — mirror NDArray's surface
    # ------------------------------------------------------------------
    def _binop(self, other, op, scalar_op, reverse=False):
        from . import _invoke_op, _invoke_scalar
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _invoke_op(op, [a, b])
        from ..base import numeric_types
        if isinstance(other, numeric_types):
            return _invoke_scalar(scalar_op, self, float(other), reverse)
        return NotImplemented

    def __add__(self, o): return self._binop(o, "broadcast_add", "_plus_scalar")
    def __radd__(self, o): return self._binop(o, "broadcast_add", "_plus_scalar", True)
    def __sub__(self, o): return self._binop(o, "broadcast_sub", "_minus_scalar")
    def __rsub__(self, o): return self._binop(o, "broadcast_sub", "_minus_scalar", True)
    def __mul__(self, o): return self._binop(o, "broadcast_mul", "_mul_scalar")
    def __rmul__(self, o): return self._binop(o, "broadcast_mul", "_mul_scalar", True)
    def __truediv__(self, o): return self._binop(o, "broadcast_div", "_div_scalar")
    def __rtruediv__(self, o): return self._binop(o, "broadcast_div", "_div_scalar", True)
    def __pow__(self, o): return self._binop(o, "broadcast_power", "_power_scalar")
    def __neg__(self): return self._binop(-1.0, None, "_mul_scalar")
    def __eq__(self, o): return self._binop(o, "broadcast_equal", "_equal_scalar")
    def __ne__(self, o): return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")
    def __gt__(self, o): return self._binop(o, "broadcast_greater", "_greater_scalar")
    def __ge__(self, o): return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")
    def __lt__(self, o): return self._binop(o, "broadcast_lesser", "_lesser_scalar")
    def __le__(self, o): return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # pickling rides the JSON wire format (the reference pickles through
    # tojson/load_json the same way, symbol.py __getstate__): _Node/OpDef
    # object graphs never enter the pickle, so compiled-cache handles and
    # op closures can't leak in
    def __getstate__(self):
        return {"handle": self.tojson()}

    def __setstate__(self, state):
        self._entries = load_json(state["handle"])._entries

    def __repr__(self):
        outs = self.list_outputs()
        return "<Symbol %s>" % (self.name or ("group [%s]" % ", ".join(outs[:4])))

    # common method surface delegating to ops
    def _unary(self, op, **attrs):
        from . import _invoke_op
        return _invoke_op(op, [self], attrs)

    def reshape(self, shape, **kw): return self._unary("Reshape", shape=tuple(shape))
    def astype(self, dtype): return self._unary("Cast", dtype=str(_np.dtype(dtype)))
    def transpose(self, axes=()): return self._unary("transpose", axes=tuple(axes))
    def flatten(self): return self._unary("Flatten")
    def sum(self, axis=None, keepdims=False):
        return self._unary("sum", axis=axis, keepdims=keepdims)
    def mean(self, axis=None, keepdims=False):
        return self._unary("mean", axis=axis, keepdims=keepdims)
    def max(self, axis=None, keepdims=False):
        return self._unary("max", axis=axis, keepdims=keepdims)
    def slice_axis(self, axis, begin, end):
        return self._unary("slice_axis", axis=axis, begin=begin, end=end)
    def expand_dims(self, axis): return self._unary("expand_dims", axis=axis)
    def squeeze(self, axis=None): return self._unary("squeeze", axis=axis)
    def softmax(self, axis=-1): return self._unary("softmax", axis=axis)
    def exp(self): return self._unary("exp")
    def log(self): return self._unary("log")
    def sqrt(self): return self._unary("sqrt")
    def square(self): return self._unary("square")
    def abs(self): return self._unary("abs")
    def sigmoid(self): return self._unary("sigmoid")
    def tanh(self): return self._unary("tanh")
    def relu(self): return self._unary("relu")


def _attr_to_str(v):
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, (tuple, list)):
        return "(" + ", ".join(str(x) for x in v) + ")"
    if v is None:
        return "None"
    return str(v)


# the user attrs the framework itself consumes in dunder form
# (optimizer lr/wd multipliers, the mirroring hint) — a plain-spelled
# one is mirrored to its dunder twin at store time, like the reference
_MIRRORED_USER_ATTRS = ("lr_mult", "wd_mult", "force_mirroring")


def _expand_user_attrs(attrs):
    """Mirror recognized plain keys to their dunder twins so both
    spellings list (conformance: test_attr reads attr('lr_mult') and
    attr('__lr_mult__') after setting either one)."""
    out = dict(attrs)
    for key in _MIRRORED_USER_ATTRS:
        if key in out and ("__%s__" % key) not in out:
            out["__%s__" % key] = str(out[key])
    return out


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    if not isinstance(name, str):
        raise TypeError("Variable name must be a string")
    str_attrs = AttrScope.current_attrs()
    if attr:
        str_attrs.update({k: str(v) for k, v in attr.items()})
    str_attrs = _expand_user_attrs(str_attrs)
    if shape is not None:
        str_attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        str_attrs["__dtype__"] = str(_np.dtype(dtype))
    if lr_mult is not None:
        str_attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        str_attrs["__wd_mult__"] = str(wd_mult)
    if init is not None:
        str_attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    for k, v in kwargs.items():
        if k.startswith("__") and k.endswith("__"):
            # free-form dunder kwargs attach as user attrs (reference
            # symbol.py var(): "Additional attributes must start and end
            # with double underscores")
            str_attrs[k] = str(v)
        else:
            raise ValueError(
                "Variable attribute name=%s is not supported. Additional "
                "attributes must start and end with double underscores, "
                "e.g. __yourattr__" % k)
    node = _Node(None, name, {}, [], str_attrs)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    """Parse a graph JSON, including every legacy layout the reference
    upgrades in src/nnvm/legacy_json_util.cc:43 (UpgradeJSON_*): op
    params under "param" (pre-0.9), user attrs under "attr" (0.9-1.1),
    and the merged "attrs" dict (1.2+) whose values are MXNet-style
    strings like "(3, 3)" / "True" (coerced per-op by
    OpDef.normalize_attrs)."""
    return _load_graph_dict(json.loads(json_str))


def _load_graph_dict(data):
    jnodes = data["nodes"]
    nodes = []
    for jn in jnodes:
        attrs = dict(jn.get("param", {}) or {})
        attrs.update(jn.get("attr", {}) or {})
        attrs.update(jn.get("attrs", {}) or {})
        inputs = [(nodes[i], oi) for i, oi, *_ in jn["inputs"]]
        if jn["op"] == "null":
            nodes.append(_Node(None, jn["name"], {}, [], attrs))
        elif jn.get("subgraphs"):
            # control-flow node: rebuild the lax lowering from the
            # nested graphs + the cf_meta recipe (contrib._rebuild_cf);
            # user attrs (ctx_group, ...) pass through
            from . import contrib as _cf
            subs = [_load_graph_dict(g) for g in jn["subgraphs"]]
            meta = json.loads(attrs["cf_meta"])
            meta["subgraphs"] = subs
            opdef, n_out = _cf._rebuild_cf(jn["op"], meta)
            user = {k: v for k, v in attrs.items() if k != "cf_meta"}
            nodes.append(_Node(opdef, jn["name"], {}, inputs,
                               str_attrs=user, cf_meta=meta))
        else:
            opdef = _reg.get_op(jn["op"])
            given = [k for k in attrs if k in opdef.attr_names]
            typed = opdef.normalize_attrs({k: attrs[k] for k in given})
            user = {k: v for k, v in attrs.items() if k not in opdef.attr_names}
            nodes.append(_Node(opdef, jn["name"], typed, inputs, user,
                               given_attrs=given))
    heads = data["heads"]
    return Symbol([(nodes[h[0]], h[1]) for h in heads])
