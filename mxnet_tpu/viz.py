"""mx.viz alias (the reference exposes visualization as mx.viz)."""
from .visualization import print_summary, plot_network  # noqa: F401
