"""Testing utilities.

Reference parity: python/mxnet/test_utils.py — the op-correctness harness
(SURVEY.md §4): ``check_numeric_gradient`` (central differences vs autodiff,
ref :792), ``check_symbolic_forward/backward`` (:925,:999), and
``check_consistency`` (:1207 — same op across backend contexts; here
CPU-XLA vs TPU-XLA replaces cpu-vs-gpu-vs-cudnn).
"""
from __future__ import annotations

import numpy as np

from .context import Context, cpu, current_context
from .ndarray import array as nd_array
from .base import MXNetError

__all__ = ["default_context", "set_default_context", "default_dtype",
           "get_atol", "get_rtol", "assert_almost_equal", "rand_ndarray",
           "rand_shape_nd", "rand_shape_2d", "rand_shape_3d",
           "random_arrays", "random_sample", "np_reduce",
           "find_max_violation", "almost_equal_ignore_nan",
           "assert_almost_equal_ignore_nan", "assert_exception", "retry",
           "list_gpus", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward",
           "check_consistency", "almost_equal", "same", "simple_forward"]


def default_context():
    return current_context()


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return np.allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-6, names=("a", "b"),
                        equal_nan=False):
    a = a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)
    b = b.asnumpy() if hasattr(b, "asnumpy") else np.asarray(b)
    if not np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        idx = np.unravel_index(np.argmax(np.abs(a - b)), a.shape) if a.shape else ()
        raise AssertionError(
            "%s and %s differ: max |diff|=%g at %s (%s vs %s), rtol=%g atol=%g"
            % (names[0], names[1], float(np.max(np.abs(a - b))), idx,
               a[idx] if a.shape else a, b[idx] if b.shape else b, rtol, atol))


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype="float32", ctx=None):
    if stype != "default":
        raise MXNetError("sparse rand_ndarray not yet supported")
    return nd_array(np.random.uniform(-1, 1, size=shape).astype(dtype), ctx=ctx)


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    shapes = {k: v.shape for k, v in inputs.items()}
    ex = sym.simple_bind(ctx or default_context(), "null", **shapes)
    outs = ex.forward(is_train=is_train,
                      **{k: nd_array(v) for k, v in inputs.items()})
    outs = [o.asnumpy() for o in outs]
    return outs[0] if len(outs) == 1 else outs


def _exec_for(sym, location, aux_states, grad_req, ctx):
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    args = {k: nd_array(np.asarray(v), ctx=ctx) for k, v in location.items()}
    arg_shapes = {k: v.shape for k, v in args.items()}
    ex = sym.simple_bind(ctx, grad_req, **arg_shapes)
    for k, v in args.items():
        ex.arg_dict[k]._set_data(v._data)
    if aux_states:
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(sym.list_auxiliary_states(), aux_states))
        for k, v in aux_states.items():
            ex.aux_dict[k]._set_data(nd_array(np.asarray(v), ctx=ctx)._data)
    return ex, location


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-6,
                           aux_states=None, ctx=None, is_train=False):
    ctx = ctx or default_context()
    ex, _ = _exec_for(sym, location, aux_states, "null", ctx)
    outputs = ex.forward(is_train=is_train)
    for out, exp in zip(list(outputs), expected):
        assert_almost_equal(out, exp, rtol, atol)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-6, aux_states=None, grad_req="write",
                            ctx=None):
    ctx = ctx or default_context()
    ex, loc = _exec_for(sym, location, aux_states, grad_req, ctx)
    ex.forward(is_train=True)
    ex.backward([nd_array(np.asarray(g), ctx=ctx) for g in out_grads])
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    for name, exp in expected.items():
        if exp is None:
            continue
        assert_almost_equal(ex.grad_dict[name], exp, rtol, atol,
                            names=("grad(%s)" % name, "expected"))
    return {k: v.asnumpy() for k, v in ex.grad_dict.items()}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, ctx=None,
                           dtype="float64"):
    """Central-difference gradient check against the executor's autodiff
    (reference test_utils.py:792). The symbol's scalar loss is
    sum(outputs * fixed_random_projection) so multi-output syms work."""
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    location = {k: np.asarray(v, dtype="float32") for k, v in location.items()}
    grad_nodes = grad_nodes or [n for n in arg_names
                                if np.issubdtype(location[n].dtype, np.floating)]

    grad_req = {n: ("write" if n in grad_nodes else "null") for n in arg_names}
    ex, _ = _exec_for(sym, location, aux_states, grad_req, ctx)
    outputs = list(ex.forward(is_train=True))
    projs = [np.random.normal(0, 1, size=o.shape).astype("float32")
             for o in outputs]
    ex.backward([nd_array(p, ctx=ctx) for p in projs])
    sym_grads = {n: ex.grad_dict[n].asnumpy() for n in grad_nodes}

    ex_probe, _ = _exec_for(sym, location, aux_states, "null", ctx)

    def loss_at(loc):
        outs = ex_probe.forward(is_train=True,
                                **{k: nd_array(v, ctx=ctx) for k, v in loc.items()})
        return sum(float(np.sum(o.asnumpy() * p)) for o, p in zip(list(outs), projs))

    for name in grad_nodes:
        base = location[name]
        num_grad = np.zeros_like(base, dtype="float64")
        flat = base.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            loc_p = {k: v.copy() for k, v in location.items()}
            loc_p[name].reshape(-1)[i] = orig + numeric_eps
            loss_p = loss_at(loc_p)
            loc_m = {k: v.copy() for k, v in location.items()}
            loc_m[name].reshape(-1)[i] = orig - numeric_eps
            loss_m = loss_at(loc_m)
            num_grad.reshape(-1)[i] = (loss_p - loss_m) / (2 * numeric_eps)
        assert_almost_equal(sym_grads[name], num_grad, rtol,
                            atol if atol is not None else 1e-2,
                            names=("autodiff(%s)" % name, "numeric(%s)" % name))


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, rtol=1e-4, atol=1e-5):
    """Run the same symbol on multiple contexts and compare outputs+grads
    (reference test_utils.py:1207 cpu/gpu/cudnn cross-check)."""
    results = []
    for spec in ctx_list:
        spec = dict(spec)
        ctx = spec.pop("ctx")
        type_dict = spec.pop("type_dict", {})
        shapes = spec
        ex = sym.simple_bind(ctx, grad_req, type_dict=type_dict, **shapes)
        if not results:
            # seed shared random params from the first context
            arg_vals = {n: np.random.normal(0, scale, size=a.shape).astype("float32")
                        for n, a in ex.arg_dict.items()}
            if arg_params:
                arg_vals.update({k: np.asarray(v) for k, v in arg_params.items()})
        for n, a in ex.arg_dict.items():
            a._set_data(nd_array(arg_vals[n].astype(a.dtype), ctx=ctx)._data)
        outs = ex.forward(is_train=(grad_req != "null"))
        if grad_req != "null":
            ex.backward([nd_array(np.ones(o.shape, dtype="float32"), ctx=ctx)
                         for o in list(outs)])
            grads = {n: g.asnumpy() for n, g in ex.grad_dict.items() if g is not None}
        else:
            grads = {}
        results.append(([o.asnumpy() for o in list(outs)], grads))
    ref_outs, ref_grads = results[0]
    for outs, grads in results[1:]:
        for a, b in zip(ref_outs, outs):
            assert_almost_equal(a, b, rtol, atol)
        for n in ref_grads:
            assert_almost_equal(ref_grads[n], grads[n], rtol, atol,
                                names=("grad_%s" % n, "grad_%s'" % n))
    return results


def set_default_context(ctx):
    """Make ``ctx`` the fallback default (ref test_utils.py
    set_default_context). Does NOT touch the ``with ctx:`` stack —
    an active with-block still wins, and leaving it must not discard
    this default."""
    from . import context as _context
    _context._default_override = ctx


def default_dtype():
    return np.float32


def get_atol(atol=None):
    return 1e-20 if atol is None else atol


def get_rtol(rtol=None):
    return 1e-5 if rtol is None else rtol


def random_arrays(*shapes):
    """Random float32 numpy arrays (scalars for () shapes); one array or
    a list (ref test_utils.py random_arrays)."""
    arrays = [np.array(np.random.randn(), dtype=np.float32) if not s
              else np.random.randn(*s).astype(np.float32) for s in shapes]
    return arrays[0] if len(arrays) == 1 else arrays


def random_sample(population, k):
    """Sample without replacement, order preserved by shuffle semantics
    (ref test_utils.py random_sample)."""
    import random as _random
    sample = list(population)
    _random.shuffle(sample)
    return sample[:k]


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Apply a numpy reduce over (possibly multiple) axes with MXNet's
    keepdims semantics (ref test_utils.py np_reduce)."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def find_max_violation(a, b, rtol=None, atol=None):
    """Location + value of the worst |a-b| relative violation
    (ref test_utils.py find_max_violation)."""
    rtol, atol = get_rtol(rtol), get_atol(atol)
    diff = np.abs(a - b)
    tol = atol + rtol * np.abs(b)
    violation = diff / (tol + 1e-20)
    loc = np.unravel_index(np.argmax(violation), violation.shape)
    return loc, np.max(violation)


def almost_equal_ignore_nan(a, b, rtol=None, atol=None):
    """almost_equal over the non-NaN entries only (ref test_utils.py
    almost_equal_ignore_nan)."""
    a = np.copy(a)
    b = np.copy(b)
    nan_mask = np.logical_or(np.isnan(a), np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    return almost_equal(a, b, get_rtol(rtol), get_atol(atol))


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None,
                                   names=("a", "b")):
    a = np.copy(a)
    b = np.copy(b)
    nan_mask = np.logical_or(np.isnan(a), np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    assert_almost_equal(a, b, get_rtol(rtol), max(get_atol(atol), 1e-20),
                        names)


def assert_exception(f, exception_type, *args, **kwargs):
    """Assert f(*args, **kwargs) raises exception_type (ref
    test_utils.py assert_exception)."""
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    # raised OUTSIDE the try: must not be swallowed when the expected
    # type is AssertionError/Exception itself
    raise AssertionError("%s did not raise %s"
                         % (f, exception_type.__name__))


def retry(n):
    """Decorator retrying a flaky (random) test up to n times (ref
    test_utils.py retry)."""
    if n <= 0:
        raise ValueError("n must be positive")

    def decorate(f):
        import functools

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError as e:
                    if i == n - 1:
                        raise e
        return wrapper

    return decorate


def list_gpus():
    """Indices of visible accelerator devices — TPUs here (ref
    test_utils.py list_gpus returns CUDA ordinals)."""
    import jax
    return list(range(len([d for d in jax.local_devices()
                           if d.platform != "cpu"])))
