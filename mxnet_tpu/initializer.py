"""Weight initializers (reference parity: python/mxnet/initializer.py).

Same registry + ``InitDesc``-style name-pattern dispatch as the reference:
names ending in _weight/_bias/_gamma/_beta/_moving_* get the matching rule.
"""
from __future__ import annotations

import json
import re

import numpy as _np

from .base import MXNetError

__all__ = ["Initializer", "Uniform", "Normal", "Zero", "One", "Constant",
           "Xavier", "MSRAPrelu", "Orthogonal", "Load", "Mixed", "InitDesc",
           "register", "Bilinear", "LSTMBias", "FusedRNN"]

_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def get(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if name is None:
        return Uniform(0.07)
    key = str(name).lower()
    if key not in _INIT_REGISTRY:
        raise MXNetError("unknown initializer '%s'" % name)
    return _INIT_REGISTRY[key](**kwargs)


class InitDesc(str):
    """Parameter name + attrs hint (reference initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        init_attr = desc.attrs.get("__init__", "")
        if init_attr:
            klass, kwargs = json.loads(init_attr)
            get(klass, **kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("_weight"):
            self._init_weight(desc, arr)
        elif name.endswith("_bias"):
            self._init_bias(desc, arr)
        elif name.endswith("_gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("_beta"):
            self._init_beta(desc, arr)
        elif name.endswith("_moving_mean") or name.endswith("_running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("_moving_var") or name.endswith("_running_var"):
            self._init_one(desc, arr)
        elif name.endswith("_moving_avg"):
            self._init_zero(desc, arr)
        elif name.endswith("_min") or name.endswith("_max"):
            self._init_zero(desc, arr)
        elif name.endswith("_parameters"):
            # the fused RNN op's packed 1-D parameter vector (ops/rnn.py,
            # cuDNN packed-weight parity). Delegates to _init_default so
            # Zero/Constant/FusedRNN keep their semantics; initializers
            # whose structured rule needs >=2-D fan info (Xavier)
            # override _init_rnn_packed with a small-uniform fallback
            self._init_rnn_packed(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- rules ----------------------------------------------------------
    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_rnn_packed(self, name, arr):
        self._init_default(name, arr)

    def _init_bias(self, name, arr):
        arr[:] = 0.0

    def _init_gamma(self, name, arr):
        arr[:] = 1.0

    def _init_beta(self, name, arr):
        arr[:] = 0.0

    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_default(self, name, arr):
        self._init_weight(name, arr)

    def __repr__(self):
        return "%s(%s)" % (self.__class__.__name__, self._kwargs)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = _np.random.uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = _np.random.normal(0, self.sigma, arr.shape)


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


# reference aliases (python/mxnet/initializer.py: @register(alias) usage)
_INIT_REGISTRY["zeros"] = Zero
_INIT_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference initializer.py Xavier; default for vision)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_rnn_packed(self, name, arr):
        # the packed 1-D fused-RNN vector has no fan structure for the
        # Xavier rule; small uniform matches the reference examples'
        # default for raw RNN params
        arr[:] = _np.random.uniform(-0.07, 0.07, arr.shape)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError("Xavier requires >=2D weight, got %s for %s"
                             % (shape, name))
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        scale = _np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = _np.random.uniform(-scale, scale, shape)
        else:
            arr[:] = _np.random.normal(0, scale, shape)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        Initializer.__init__(self, factor_type=factor_type, slope=slope)
        self.rnd_type = "gaussian"
        self.factor_type = factor_type
        self.magnitude = magnitude


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        weight = _np.zeros(arr.size, dtype="float32")
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(arr.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)


@register
class Load(Initializer):
    def __init__(self, param, default_init=None, verbose=False):
        self.param = param
        self.default_init = default_init

    def __call__(self, name, arr):
        name = str(name)
        for key in (name, "arg:" + name, "aux:" + name):
            if key in self.param:
                src = self.param[key]
                if src.shape != arr.shape:
                    raise MXNetError("shape mismatch loading %s" % name)
                arr[:] = src.asnumpy() if hasattr(src, "asnumpy") else src
                return
        if self.default_init is None:
            raise MXNetError("no init value for %s" % name)
        self.default_init(InitDesc(name), arr)


@register
class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise MXNetError("no initializer matches %s" % name)


@register
class LSTMBias(Initializer):
    """Init forget-gate bias to a constant (cuDNN gate order i,f,g,o)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_bias(self, name, arr):
        arr[:] = 0.0
        num_hidden = arr.shape[0] // 4
        # asnumpy() views the immutable JAX buffer — copy before editing
        a = _np.array(arr.asnumpy() if hasattr(arr, "asnumpy") else arr)
        a[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = a

    _init_default = _init_bias
    _init_weight = _init_bias


class FusedRNN(Initializer):
    """Initialize the flat fused-RNN parameter vector by delegating to a
    base initializer per sub-matrix (reference initializer.py FusedRNN)."""

    def __init__(self, init, num_hidden, num_layers, mode, bidirectional=False,
                 forget_bias=1.0):
        super().__init__()
        self._init = get(init) if not isinstance(init, Initializer) else init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .ops.rnn import _NGATES
        ngates = _NGATES[self._mode]
        H = self._num_hidden
        flat = arr.asnumpy().ravel()
        # weights: uniform; biases: zero (+forget bias for lstm)
        total = flat.size
        nbias_per = ngates * H
        ndir = 2 if self._bidirectional else 1
        n_bias = self._num_layers * ndir * 2 * nbias_per
        wpart = _np.random.uniform(-0.07, 0.07, total - n_bias)
        bpart = _np.zeros(n_bias, dtype="float32")
        if self._mode == "lstm":
            for blk in range(self._num_layers * ndir * 2):
                bpart[blk * nbias_per + H: blk * nbias_per + 2 * H] = \
                    self._forget_bias
        arr[:] = _np.concatenate([wpart, bpart]).reshape(arr.shape)

    _init_default = _init_weight
