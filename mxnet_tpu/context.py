"""Device contexts: ``mx.tpu(i)`` as a first-class context.

Reference parity: include/mxnet/base.h Context (kCPU/kGPU/kCPUPinned) and
python/mxnet/context.py. The TPU-native realization maps a Context onto a
concrete ``jax.Device``; there is no separate storage layer because XLA owns
HBM allocation (reference src/storage/ is replaced by the XLA allocator).
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_gpus", "num_tpus"]


class Context:
    """Execution device descriptor.

    Parameters
    ----------
    device_type : str
        'cpu', 'tpu', or 'gpu' ('gpu' is accepted for API compatibility and
        resolves to the accelerator backend when one exists).
    device_id : int
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared",
                   6: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3,
                   "cpu_shared": 5, "tpu": 6}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    # -- JAX mapping ------------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        return _resolve_device(self)

    def __enter__(self):
        if not hasattr(Context._default_ctx, "contexts"):
            Context._default_ctx.contexts = []
        Context._default_ctx.contexts.append(self)
        return self

    def __exit__(self, *args):
        Context._default_ctx.contexts.pop()

    def empty_cache(self):
        # XLA manages HBM; provided for API parity.
        pass


def _accelerators():
    # local_devices: in a multi-process (jax.distributed) world a Context
    # must name a device THIS process owns; identical to jax.devices()
    # when single-process
    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    return devs if devs else jax.local_devices()


def _resolve_device(ctx: Context) -> jax.Device:
    if ctx.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
        cpus = [d for d in jax.local_devices() if d.platform == "cpu"]
        if not cpus:
            # accelerator-platform processes still carry a host backend;
            # mx.cpu() arrays MUST live there — a fallback to the
            # accelerator would silently turn every data-iterator batch
            # into device traffic. local_devices(backend=...) keeps this
            # process's own cpu device in a jax.distributed world
            # (jax.devices("cpu") would return rank 0's).
            try:
                cpus = jax.local_devices(backend="cpu")
            except RuntimeError:
                cpus = jax.local_devices()  # truly no host backend
        return cpus[min(ctx.device_id, len(cpus) - 1)]
    devs = _accelerators()
    if ctx.device_id >= len(devs):
        raise MXNetError(
            "Context %s out of range: %d device(s) visible" % (ctx, len(devs)))
    return devs[ctx.device_id]


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Accepted for compatibility; resolves to the accelerator backend."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def num_gpus():
    return len([d for d in jax.local_devices() if d.platform != "cpu"])


def num_tpus():
    return num_gpus()


# process-wide fallback installed by test_utils.set_default_context;
# the `with ctx:` stack always takes precedence
_default_override = None


def current_context() -> Context:
    if getattr(Context._default_ctx, "contexts", None):
        return Context._default_ctx.contexts[-1]
    if _default_override is not None:
        return _default_override
    return default_context()


def default_context() -> Context:
    """Default = first accelerator if present else cpu (TPU-first stance)."""
    if any(d.platform != "cpu" for d in jax.local_devices()):
        return Context("tpu", 0)
    return Context("cpu", 0)
