"""DecodeEngine: continuous-batching generation with chunked prefill.

One engine owns (a) a paged KV cache (``cache.PagedKVCache`` + the
per-layer device arrays) and (b) ONE compiled *mixed* step bound at a
fixed slot capacity — ``models.transformer.get_mixed_step_symbol`` —
that every iteration processes up to K prefill-chunk tokens of one
admitted prompt AND one decode token for every active slot in the same
donated launch (Sarathi-Serve-style stall-free scheduling: prompt
processing piggybacks on the memory-bound decode iteration instead of
monopolizing the device for a full-prompt prefill).  The pow2 prefill
ladder this replaced cost one compiled program per bucket and stalled
every in-flight stream for the length of the longest prompt.

Execution discipline (the PR 2/3 invariant, extended to serving):

* every iteration is exactly ONE device launch — the compiled mixed
  step runs all slots plus the current prompt chunk; padded slots ride
  along masked (position -1), an empty chunk rides along with
  ``chunk_len == 0``;
* sequence raggedness (positions, chunk offsets/lengths, block tables)
  enters as runtime arrays, so steady state NEVER retraces — witnessed
  by ``decode_retraces``, which counts only retraces after each
  program's first (expected) compile;
* the only per-iteration host sync is reading the sampled token back
  (that readback *is* the streamed response); a completed prefill adds
  one first-token readback per ADMISSION, not per step.

Scheduling policy lives in ``scheduler.py``; this module is the device
half: mixed-step dispatch, cache threading (each step's new cache
arrays replace the bound inputs via ``NDArray._set_data``, so every
iteration sees one coherent cache), sampling, and telemetry.
"""
from __future__ import annotations

import collections as _collections
import threading
import time

import numpy as _np

from ..base import MXNetError
from ..pallas.dispatch import paged_attn_impl as _paged_attn_impl
from ..serving.batcher import (DeadlineExceededError, QueueFullError,
                               ServerClosedError, percentile as _percentile)
from ..telemetry import REGISTRY, tracing as _tracing
from .cache import CacheOOMError, PagedKVCache
from .scheduler import Scheduler, Sequence
from .spec import (ACCEPT_RATE, SPEC_ACCEPTED, SPEC_PROPOSED,
                   TOKENS_PER_LAUNCH, choose_spec_impl, make_drafter)

__all__ = ["DecodeEngine"]

QUEUE_DEPTH = REGISTRY.gauge(
    "decode_queue_depth", "sequences waiting for a decode slot",
    unit="sequences")
ACTIVE_SEQS = REGISTRY.gauge(
    "decode_active_sequences", "sequences occupying decode slots",
    unit="sequences")
ADMITTED = REGISTRY.counter(
    "decode_admitted", "sequences accepted into the wait queue")
COMPLETED = REGISTRY.counter(
    "decode_completed", "sequences finished (eos or length)")
FAILED = REGISTRY.counter(
    "decode_failed", "sequences failed (cache OOM, engine stop, error)")
EXPIRED = REGISTRY.counter(
    "decode_expired", "sequences expired before finishing (deadline)")
CANCELLED = REGISTRY.counter(
    "decode_cancelled", "sequences cancelled by the client "
    "(StreamHandle.cancel / dropped HTTP stream)")
PREFILLS = REGISTRY.counter(
    "decode_prefills", "prompts admitted into chunked prefill "
    "(admissions + preemption recomputes)")
PREFILL_CHUNKS = REGISTRY.counter(
    "decode_prefill_chunks", "prompt chunks processed by mixed decode "
    "steps (chunked prefill — one per iteration with a prompt in "
    "flight)")
CHUNK_BUDGET = REGISTRY.gauge(
    "decode_chunk_tokens", "per-iteration chunked-prefill token budget "
    "(MXNET_DECODE_CHUNK, pow2-padded)", unit="tokens")
PREEMPTIONS = REGISTRY.counter(
    "decode_preemptions", "sequences preempted-by-recompute on cache "
    "pressure")
STEPS = REGISTRY.counter(
    "decode_steps", "decode iterations dispatched (one compiled launch "
    "each)")
TOKENS = REGISTRY.counter(
    "decode_tokens", "tokens generated (prefill first-tokens included)")
STEP_MS = REGISTRY.histogram(
    "decode_step_ms", "wall time of one decode iteration (dispatch + "
    "token readback + bookkeeping)", unit="ms")
TTFT_MS = REGISTRY.histogram(
    "decode_ttft_ms", "time to first token (submit -> first streamed "
    "token, queue wait included)", unit="ms")
RETRACES = REGISTRY.counter(
    "decode_retraces", "decode/prefill program retraces AFTER each "
    "program's first compile — pinned at zero by tests", vital=True)
RELOADS = REGISTRY.counter(
    "decode_reloads", "successful hot weight reloads into a live engine")
TTFT_STEPS = REGISTRY.histogram(
    "decode_ttft_steps", "steps to first token (submit -> first emit, "
    "in mixed-step iterations) — the dispatch-count TTFT witness "
    "sentinel SLO rules watch (wall-clock is bandwidth noise in CPU "
    "containers)", unit="steps",
    bounds=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
ACCEPT_WINDOW = REGISTRY.gauge(
    "decode_accept_rate_window", "accepted/proposed draft-token ratio "
    "over the last MXNET_DECODE_ACCEPT_WINDOW slot-spans (default 256) "
    "— the sentinel's drift witness; decode_accept_rate is cumulative "
    "and cannot recover after a bad stretch", unit="ratio")


def _chunk_budget(chunk_tokens, max_context):
    """Resolve the per-iteration prefill-chunk token budget K:
    explicit arg > ``MXNET_DECODE_CHUNK`` > 64, capped at the context
    length and padded up to a power of two (one bind-time geometry —
    every chunk rides the same compiled mixed step)."""
    import os
    ck = chunk_tokens
    if ck is None:
        ck = int(os.environ.get("MXNET_DECODE_CHUNK", "0") or 0)
    ck = int(ck) if int(ck or 0) > 0 else 64
    p = 1
    while p < ck:
        p *= 2
    return min(p, int(max_context))


class DecodeEngine:
    """Generative serving engine for the decoder-only transformer
    (module docstring; knobs in docs/DECODE.md).

    Parameters
    ----------
    arg_params : training-checkpoint parameters (name -> NDArray/numpy)
    model_config : the ``transformer.get_symbol`` kwargs this checkpoint
        was trained with (num_classes, num_layers, d_model, num_heads,
        ffn_dim, seq_len, ...) — ``seq_len`` doubles as the maximum
        context length a sequence may reach.
    capacity : fixed decode batch slots (the compiled step's batch dim)
    block_size, num_blocks : KV-cache geometry (per layer, K and V each
        are ``(num_blocks, block_size, H, D)``)
    chunk_tokens : per-iteration prefill-chunk token budget K (default:
        ``MXNET_DECODE_CHUNK`` or 64; pow2-padded, capped at seq_len).
        Any prompt under ``seq_len`` is admissible — it prefills over
        ``ceil(len/K)`` mixed iterations without stalling decode.
    max_prefill_len, prefill_buckets : accepted-but-ignored (the pow2
        prefill ladder these configured is retired; chunked prefill
        serves every prompt length through the one mixed step)
    admission : 'continuous' (default) or 'static' (run-to-completion —
        the A/B baseline for bench --mode decode)
    eos_id : default end-of-sequence token id (None = length-stop only)
    """

    def __init__(self, arg_params, model_config, capacity=8, block_size=16,
                 num_blocks=64, chunk_tokens=None, max_prefill_len=None,
                 prefill_buckets=None, ctx=None, eos_id=None,
                 max_waiting=256, admission="continuous",
                 default_max_new_tokens=64, warmup=False, start=True,
                 spec_k=None, spec_impl=None, prefix_cache=None,
                 draft_params=None, draft_config=None):
        import os as _os
        from ..context import current_context
        from ..models import transformer
        from ..ndarray.ndarray import NDArray

        self._cfg = dict(model_config)
        self._cfg.pop("dropout", None)          # inference graphs
        self._ctx = ctx if ctx is not None else current_context()
        self.capacity = int(capacity)
        self._eos = eos_id
        self._default_max_new = int(default_max_new_tokens)
        self._max_context = int(self._cfg.get("seq_len", 1024))
        self._num_layers = int(self._cfg.get("num_layers", 12))
        bs = int(block_size)
        self._table_width = -(-self._max_context // bs)
        self._chunk_tokens = _chunk_budget(chunk_tokens,
                                           self._max_context)
        CHUNK_BUDGET.set(self._chunk_tokens)

        # --- speculative decoding + prefix sharing knobs (both default
        # OFF: docs/DECODE.md).  spec_k > 0 binds the span-verify step
        # (S = spec_k + 1 tokens per slot per launch) instead of the
        # one-token mixed step; the drafter follows the auto/force/off
        # contract of pallas.dispatch.choose_impl.
        from .. import config as _config
        if spec_k is None:
            spec_k = int(_os.environ.get("MXNET_DECODE_SPEC_K", "0") or 0)
        self._spec_k = max(int(spec_k), 0)
        self._spec_impl = None
        self._drafter = None
        if self._spec_k > 0:
            raw = (spec_impl if spec_impl is not None
                   else _os.environ.get("MXNET_DECODE_SPEC_IMPL", "auto"))
            self._spec_impl = choose_spec_impl(raw,
                                               draft_params is not None)
            if self._spec_impl is None:      # MXNET_DECODE_SPEC_IMPL=off
                self._spec_k = 0
            else:
                self._drafter = make_drafter(
                    self._spec_impl, draft_params, draft_config,
                    ctx=self._ctx, forced=(raw == "draft"))
                self._spec_impl = self._drafter.name
        self._span = self._spec_k + 1
        if prefix_cache is None:
            prefix_cache = _config.env_bool("MXNET_DECODE_PREFIX_CACHE",
                                            default=False)
        self._prefix_cache = bool(prefix_cache)
        self._prefix_flush = False    # set by swap_params, drained by _tick

        self.cache = PagedKVCache(num_blocks, bs,
                                  prefix_sharing=self._prefix_cache)
        self._sched = Scheduler(self.capacity, self.cache,
                                max_waiting=max_waiting,
                                admission=admission)

        # --- bind the ONE step at fixed capacity + chunk budget: the
        # mixed step (one decode token per slot) or, with speculation
        # on, the span-verify step (S tokens per slot through the same
        # chunk-attention primitive — get_spec_step_symbol)
        if self._spec_k > 0:
            msym = transformer.get_spec_step_symbol(
                block_size=bs, num_blocks=int(num_blocks), **self._cfg)
            self._exe = msym.simple_bind(
                ctx=self._ctx, grad_req="null",
                data=(self.capacity, self._span),
                positions=(self.capacity, self._span),
                span_start=(self.capacity,),
                span_len=(self.capacity,),
                block_table=(self.capacity, self._table_width),
                chunk_data=(1, self._chunk_tokens),
                chunk_positions=(1, self._chunk_tokens),
                chunk_start=(1,), chunk_len=(1,),
                chunk_table=(1, self._table_width))
        else:
            msym = transformer.get_mixed_step_symbol(
                block_size=bs, num_blocks=int(num_blocks), **self._cfg)
            self._exe = msym.simple_bind(
                ctx=self._ctx, grad_req="null", data=(self.capacity, 1),
                positions=(self.capacity, 1),
                block_table=(self.capacity, self._table_width),
                chunk_data=(1, self._chunk_tokens),
                chunk_positions=(1, self._chunk_tokens),
                chunk_start=(1,), chunk_len=(1,),
                chunk_table=(1, self._table_width))
        self._cache_names = []
        for i in range(self._num_layers):
            self._cache_names += ["layer%d_k_cache" % i,
                                  "layer%d_v_cache" % i]
        self._cache_arrs = [self._exe.arg_dict[n] for n in self._cache_names]
        self.cache.attach_arrays(self._cache_arrs)
        # donated caches (MXNET_DECODE_DONATE, default on): the compiled
        # step takes the k/v cache buffers by donation and every dispatch
        # re-points the cache NDArrays at the step's outputs
        # (_commit_caches), so XLA updates the caches where they live —
        # no whole-cache copy in and out per token (docs/DECODE.md).
        # Block tables/positions are NOT donated: they are rebuilt
        # host-side and fed by copy each iteration.
        # ... unless the persistent compilation cache is active: disk-
        # loaded donated executables corrupt their buffers on this jax
        # version, so the guard drops donation (even against an explicit
        # MXNET_DECODE_DONATE=1) and stats() reports the truth
        # (aot.store.donation_safe, docs/AOT.md).
        from ..aot import store as _aot_store
        self._donate = (_config.env_bool("MXNET_DECODE_DONATE",
                                         default=True)
                        and _aot_store.donation_safe())
        if self._donate:
            self._donate = bool(self._exe.donate_args(self._cache_names))
        self._inputs = ("data", "positions", "block_table", "chunk_data",
                        "chunk_positions", "chunk_start", "chunk_len",
                        "chunk_table", "span_start", "span_len")
        self._weight_names = [n for n in self._exe.arg_dict
                              if n not in self._inputs
                              and n not in self._cache_names]
        self._check_params(arg_params)
        self._exe.copy_params_from(
            # analyze: ok(hostsync) checkpoint params are host-resident; one staging copy at engine construction, not on the step path
            {k: v if isinstance(v, NDArray) else NDArray(_np.asarray(v))
             for k, v in arg_params.items() if k in self._weight_names}, {},
            allow_extra_params=True)

        # accounting (instance state; registry series are process-wide)
        self._warm = set()
        self._n_steps = 0
        self._n_prefills = 0
        self._n_prefill_chunks = 0
        self._n_step_dispatches = 0
        self._occ_sum = 0
        self._cache_occ_sum = 0.0
        self._steady_retraces = 0
        self._n_tokens = 0
        # speculative accounting: slot-iterations vs slot-tokens give
        # tokens_per_launch (exactly 1.0 without speculation); proposed
        # vs accepted give the draft acceptance rate
        self._n_slot_iters = 0
        self._n_slot_tokens = 0
        self._n_spec_proposed = 0
        self._n_spec_accepted = 0
        # sliding acceptance window: (proposed, accepted) per slot-span,
        # feeding the decode_accept_rate_window sentinel gauge — the
        # cumulative ACCEPT_RATE can never recover after a bad stretch
        import os as _os
        self._spec_window = _collections.deque(
            maxlen=max(16, int(_os.environ.get(
                "MXNET_DECODE_ACCEPT_WINDOW", "256") or 256)))
        self._n_completed = 0
        self._n_failed = 0
        self._n_expired = 0
        self._n_preemptions = 0
        self._n_admitted = 0
        self._n_cancelled = 0
        # last-4096 window only: stats() p99 never reads further back,
        # and a long-lived server must not accumulate one float/request
        self._ttfts = _collections.deque(maxlen=4096)
        # steps-to-first-token (submit -> first emit, in mixed-step
        # iterations): the CPU-container TTFT witness — wall-clock there
        # is bandwidth noise, dispatch counts are exact
        self._ttft_steps = _collections.deque(maxlen=4096)
        self._rid = 0
        self._model_version = None

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._mid_admission = 0
        self._step_lock = threading.Lock()   # excludes step vs reload
        self._closing = False
        self._abort = False
        self._thread = None
        # hang watchdog over decode iterations (MXNET_WATCHDOG_FACTOR;
        # 0 = off, the default — docs/OBSERVABILITY.md)
        self._watchdog = None
        import os as _os
        if float(_os.environ.get("MXNET_WATCHDOG_FACTOR", "0") or 0) > 0:
            from ..telemetry import Watchdog
            self._watchdog = Watchdog("decode")
        if warmup:
            self.warmup()
        if start:
            self.start()

    # ------------------------------------------------------------------
    def _check_params(self, arg_params):
        missing = [n for n in self._weight_names if n not in arg_params]
        if missing:
            raise MXNetError("decode: params missing for %s"
                             % sorted(missing))
        bad = []
        for name in self._weight_names:
            v = arg_params[name]
            shape = getattr(v, "shape", None) or _np.shape(v)
            if tuple(shape) != self._exe.arg_dict[name].shape:
                bad.append(name)
        if bad:
            raise MXNetError("decode: param shapes do not match the bound "
                             "model for %s (cache layout is preserved only "
                             "across same-architecture reloads)"
                             % sorted(bad))

    def _idle_feeds(self):
        """All-slots-inactive, empty-chunk input set for the mixed step
        (warmup and tests): positions -1 mask every decode row, and
        ``chunk_len == 0`` makes the chunk stream a no-op (its zero-row
        writes re-emit existing cache bytes, so no allocator state is
        touched)."""
        K = self._chunk_tokens
        M = self._table_width
        feeds = dict(
            chunk_data=_np.zeros((1, K), _np.float32),
            chunk_positions=_np.zeros((1, K), _np.float32),
            chunk_start=_np.zeros((1,), _np.float32),
            chunk_len=_np.zeros((1,), _np.float32),
            chunk_table=_np.zeros((1, M), _np.float32))
        if self._spec_k > 0:
            # span step: span_len == 0 masks a row (chunk-attention
            # zero-length no-op), positions pad at 0 harmlessly
            feeds.update(
                data=_np.zeros((self.capacity, self._span), _np.float32),
                positions=_np.zeros((self.capacity, self._span),
                                    _np.float32),
                span_start=_np.zeros((self.capacity,), _np.float32),
                span_len=_np.zeros((self.capacity,), _np.float32),
                block_table=_np.zeros((self.capacity, M), _np.float32))
        else:
            feeds.update(
                data=_np.zeros((self.capacity, 1), _np.float32),
                positions=_np.full((self.capacity, 1), -1.0, _np.float32),
                block_table=_np.zeros((self.capacity, M), _np.float32))
        return feeds

    # ------------------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="mx-decode-engine", daemon=True)
            self._thread.start()

    def warmup(self):
        """Compile the ONE mixed step up front (vs the retired pow2
        ladder's one compile per bucket): a single all-slots-inactive,
        empty-chunk dispatch.  Runs inside an AOT-warming phase so the
        step program is flagged ``warmed`` in telemetry.programs() and,
        with MXNET_COMPILE_CACHE_DIR set, disk-loads on a restart
        (docs/AOT.md)."""
        from ..telemetry import programs as _programs
        with self._step_lock, _programs.warming():
            outs = self._exe.forward(is_train=False, **self._idle_feeds())
            # block until compiled+run; warmup exists to absorb this
            # cost before serving
            outs[1].asnumpy()  # analyze: ok(hostsync) warmup deliberately blocks until the compile+first run completes
            # donated caches: the dummy dispatch consumed the cache
            # buffers — re-point them at the outputs like any step.
            # _warm is shared with the engine thread's _dispatch
            # bookkeeping — every write holds _step_lock
            self._commit_caches(outs, base=4)
            self._warm.add("spec" if self._spec_k > 0 else "mixed")

    def aot_warm(self, manifest=None):
        """mx.aot.warm hook: the engine's step signature is fixed by its
        construction knobs, so warming is the same single dispatch
        whatever the manifest says; already-warm engines no-op.
        Returns the number of programs dispatched."""
        with self._step_lock:
            if self._warm:
                return 0
        self.warmup()
        return 1

    # ------------------------------------------------------------------
    # client API
    # ------------------------------------------------------------------
    def submit(self, tokens, max_new_tokens=None, eos_id="default",
               timeout_ms=None, temperature=0.0, seed=None, sampler=None,
               collect_logits=False, speculative=True):
        """Queue one generation; returns a :class:`StreamHandle`
        (iterate it for streamed tokens, or ``.result()`` for the full
        output).  Raises ``QueueFullError`` on backpressure and
        ``MXNetError`` for an inadmissible prompt.  ``speculative=False``
        opts this request out of draft-verify spans on a spec-enabled
        engine (it decodes one verified token per iteration)."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise MXNetError("decode: empty prompt")
        if max_new_tokens is not None and int(max_new_tokens) < 1:
            raise MXNetError("decode: max_new_tokens must be >= 1 "
                             "(got %s)" % (max_new_tokens,))
        # chunked prefill retired the max_prefill_len submit rejection:
        # ANY prompt that fits the context (with one slot to generate)
        # and whose full footprint fits the cache is admissible
        if len(tokens) >= self._max_context:
            raise MXNetError("decode: prompt of %d tokens leaves no "
                             "room to generate within seq_len=%d"
                             % (len(tokens), self._max_context))
        if self.cache.blocks_for(len(tokens)) > self.cache.num_blocks:
            raise MXNetError("decode: prompt needs %d cache blocks, the "
                             "cache only has %d"
                             % (self.cache.blocks_for(len(tokens)),
                                self.cache.num_blocks))
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms is not None else None)
        with self._cv:
            if self._closing:
                raise ServerClosedError("decode engine is stopped")
            self._rid += 1
            seq = Sequence(
                self._rid, tokens,
                max_new_tokens if max_new_tokens is not None
                else self._default_max_new,
                eos_id=self._eos if eos_id == "default" else eos_id,
                deadline=deadline, temperature=temperature, seed=seed,
                sampler=sampler, collect_logits=collect_logits,
                speculative=speculative)
            seq.submit_step = self._n_steps   # steps-to-first-token base
            self._sched.enqueue(seq)          # may raise QueueFullError
            if _tracing.enabled():
                # submit -> finish span, parented under the submitting
                # thread's context (the /generate handler's http span —
                # W3C traceparent already joined upstream callers there)
                seq.trace_span = _tracing.start_span(
                    "decode.request", rid=seq.rid,
                    prompt_len=len(tokens),
                    max_new_tokens=seq.max_new_tokens)
                seq.queue_span = _tracing.start_span(
                    "decode.queued", parent=seq.trace_span.context)
            self._n_admitted += 1
            ADMITTED.inc()
            QUEUE_DEPTH.set(len(self._sched.waiting))
            self._cv.notify_all()
        return seq.handle

    def generate(self, tokens, timeout=None, **kwargs):
        """Synchronous convenience: submit + wait; returns the
        generated token list."""
        return self.submit(tokens, **kwargs).result(timeout)

    # ------------------------------------------------------------------
    # engine thread
    # ------------------------------------------------------------------
    def _loop(self):
        while True:
            with self._cv:
                while (not self._closing
                       and not self._sched.waiting
                       and not self._sched.has_active()):
                    self._cv.wait(0.1)
                abort = self._abort
                drained = (self._closing and not self._sched.waiting
                           and not self._sched.has_active())
            # _fail_everything re-acquires _cv (a plain Lock), so it
            # must run OUTSIDE the monitor or abort deadlocks
            if abort:
                self._fail_everything(
                    ServerClosedError("decode engine stopped"))
                return
            if drained:
                return
            try:
                worked = self._tick()
            except Exception as exc:   # noqa: BLE001 — engine must survive
                self._fail_everything(exc)
                continue
            if not worked:
                time.sleep(0.002)      # blocked on cache; don't spin hot

    def _fail_everything(self, exc):
        with self._cv:
            seqs = list(self._sched.waiting)
            self._sched.waiting.clear()
        seqs += [s for _, s in self._sched.active()]
        for seq in seqs:
            self._finish(seq, error=exc)

    def _tick(self):
        """One scheduler iteration; returns False when nothing ran."""
        with self._step_lock:
            flush, self._prefix_flush = self._prefix_flush, False
        if flush:
            # deferred from swap_params: the trie's cached rows were
            # computed under the OLD weights.  Flushing here (engine
            # thread, before this tick's admissions) keeps the cache
            # single-owner; the transient is the same mixed-version
            # window hot reload already accepts for mid-prefill
            # sequences (swap_params docstring).
            self.cache.flush_prefixes()
        now = time.monotonic()
        with self._cv:
            expired = self._sched.take_expired_waiting(now)
            cancelled = [s for s in self._sched.waiting
                         if s.handle.cancelled()]
            for s in cancelled:
                self._sched.waiting.remove(s)
            QUEUE_DEPTH.set(len(self._sched.waiting))
        for seq in expired:
            self._finish(seq, error=DeadlineExceededError(
                "request %d expired before a decode slot freed" % seq.rid))
        for seq in cancelled:
            self._finish(seq, reason="cancelled")
        for _, seq in self._sched.active():
            if seq.handle.cancelled():
                self._finish(seq, reason="cancelled")
            elif seq.expired(now):
                self._finish(seq, error=DeadlineExceededError(
                    "request %d deadline expired mid-generation" % seq.rid))
        progressed = False
        batch_open = not self._sched.has_active()
        while True:
            with self._cv:
                if not self._sched.may_admit(batch_open):
                    break
                seq = self._sched.waiting[0]
                # admission gates on the FIRST chunk's footprint only —
                # chunked prefill grows the table incrementally, and
                # later chunks may preempt (youngest first) for blocks
                need = self.cache.blocks_for(
                    min(len(seq.tokens), self._chunk_tokens))
                if need > self.cache.free_count:
                    break             # FIFO: wait for blocks, no bypass
                self._sched.waiting.popleft()
                # visible to drain(): the sequence is in neither waiting
                # nor slots until place()
                self._mid_admission += 1
                QUEUE_DEPTH.set(len(self._sched.waiting))
            slot = self._sched.free_slot()
            try:
                self._admit(seq, slot)
                progressed = True
            except Exception as exc:   # noqa: BLE001 — the sequence is
                # already off the wait queue and may not be placed yet,
                # so _fail_everything would never see it: ANY failure
                # here must settle its handle, not just MXNetError
                self._finish(seq, error=exc)
            finally:
                with self._cv:
                    self._mid_admission -= 1
        # grow every DECODING sequence's block table BEFORE the step —
        # the step writes cache position seq.pos, and a missing table
        # entry would default to block 0 and corrupt whoever owns it.
        # Growth may preempt (youngest first), so re-snapshot after.
        for _, seq in self._sched.active():
            if seq.slot is None:      # preempted by an earlier growth
                continue
            if seq.n_prefilled < seq.prefill_target:
                continue              # prefilling: grown with its chunk
            try:
                self._ensure_blocks(seq, seq.pos // self.cache.block_size)
            except CacheOOMError as exc:
                self._finish(seq, error=exc)
        # pick THIS iteration's prefill chunk (oldest prefilling
        # sequence) and make sure the chunk's cache blocks exist
        chunk_seq = self._sched.pick_prefilling()
        chunk_len = 0
        if chunk_seq is not None:
            chunk_len = min(self._chunk_tokens,
                            chunk_seq.prefill_target
                            - chunk_seq.n_prefilled)
            last_row = chunk_seq.n_prefilled + chunk_len - 1
            try:
                self._ensure_blocks(chunk_seq,
                                    last_row // self.cache.block_size)
            except CacheOOMError as exc:
                self._finish(chunk_seq, error=exc)
                chunk_seq, chunk_len = None, 0
        active = self._sched.active()
        ACTIVE_SEQS.set(len(active))
        if active:
            if self._spec_k > 0:
                self._step_spec(active, chunk_seq, chunk_len)
            else:
                self._step(active, chunk_seq, chunk_len)
            progressed = True
        return progressed

    # ------------------------------------------------------------------
    def _ensure_blocks(self, seq, block_idx):
        """Make sure table entry ``block_idx`` exists, preempting the
        youngest other sequence on cache pressure."""
        while block_idx >= len(seq.blocks):
            try:
                seq.blocks += self.cache.alloc(1)
            except CacheOOMError:
                victim = self._sched.pick_victim(exclude=(seq,))
                if victim is None:
                    raise
                self._preempt(victim)

    def _preempt(self, victim):
        with self._cv:
            self._sched.preempt(victim)
            QUEUE_DEPTH.set(len(self._sched.waiting))
        if victim.prefill_span is not None:   # preempted mid-prefill
            victim.prefill_span.end(preempted=True)
            victim.prefill_span = None
        self._n_preemptions += 1
        PREEMPTIONS.inc()

    def _commit_caches(self, outs, base):
        for j, nd in enumerate(self._cache_arrs):
            nd._set_data(outs[base + j]._data)

    def _dispatch(self, exe, warm_key, **feeds):
        """Forward with retrace/dispatch accounting: the first launch of
        each program is the expected compile; anything after bumps the
        steady-state witness ``decode_retraces``.  Both counts are read
        from the executor's PER-THREAD tallies (jax traces and launches
        on the dispatching thread — this one), so another thread
        dispatching or compiling concurrently (a serving replica under
        mixed /predict traffic) can never inflate the decode
        witnesses."""
        from ..executor import _DISPATCH_TALLY, _SITE
        r0 = _SITE._tally.count
        d0 = _DISPATCH_TALLY.count
        outs = exe.forward(is_train=False, **feeds)
        dd = _DISPATCH_TALLY.count - d0
        rd = _SITE._tally.count - r0
        if warm_key in self._warm:
            if rd:
                self._steady_retraces += rd
                RETRACES.inc(rd)
        else:
            self._warm.add(warm_key)
        return outs, dd

    def _admit(self, seq, slot):
        """Place a waiting sequence into a slot for chunked prefill.

        No dispatch happens here — the mixed step carries the prompt
        into the cache one chunk per iteration, so admission is just
        bookkeeping: open the prefill span, arm the chunk cursor, and
        hand the sequence to the scheduler."""
        P = len(seq.tokens)
        if seq.queue_span is not None:
            seq.queue_span.end()
            seq.queue_span = None
        if seq.trace_span is not None:
            seq.prefill_span = _tracing.start_span(
                "decode.prefill",
                parent=getattr(seq.trace_span, "context", None),
                chunk_tokens=self._chunk_tokens, prompt_len=P,
                preemptions=seq.preemptions)
        seq.prefill_target = P
        seq.n_prefilled = 0
        seq.pos = 0
        # prefix-cache hit: adopt the trie's already-prefilled blocks
        # (COW — acquire_prefix increfs them for this sequence) and
        # start chunked prefill at the first unshared row.  At most
        # (P-1)//block_size blocks can match, so at least one prompt
        # token always prefills and the chunk head still emits the
        # sequence's first token.
        if self._prefix_cache and not seq.blocks:
            shared, rows = self.cache.acquire_prefix(seq.tokens[:P])
            if shared:
                seq.blocks = list(shared)
                seq.n_prefilled = rows
        self._n_prefills += 1
        PREFILLS.inc()
        with self._cv:
            self._sched.place(seq, slot)

    def _step(self, active, chunk_seq=None, chunk_len=0):
        t0 = time.perf_counter()
        if self._watchdog is not None:
            self._watchdog.begin()
        # per-sequence per-iteration spans: each live stream's trace
        # gets its own decode.iteration child (duration = this compiled
        # launch + readback), so one request renders submit -> prefill
        # -> N iterations -> done as a single connected tree
        it_spans = None
        if _tracing.enabled():
            it_spans = [
                _tracing.start_span(
                    "decode.iteration",
                    parent=getattr(s.trace_span, "context", None),
                    step=self._n_steps, slot=slot, pos=s.pos)
                for slot, s in active if s.trace_span is not None]
        if chunk_seq is not None and chunk_seq.slot is None:
            chunk_seq, chunk_len = None, 0   # preempted after selection
        # decode rows feed only FULLY-prefilled sequences; a sequence
        # mid-prefill rides the step at pos=-1 (inactive row) until its
        # last chunk lands, when the chunk head emits its first token
        decoding = [(slot, seq) for slot, seq in active
                    if seq.n_prefilled >= seq.prefill_target]
        data = _np.zeros((self.capacity, 1), _np.float32)
        pos = _np.full((self.capacity, 1), -1.0, _np.float32)
        table = _np.zeros((self.capacity, self._table_width), _np.float32)
        for slot, seq in decoding:
            data[slot, 0] = seq.last_token
            pos[slot, 0] = seq.pos
            table[slot, :len(seq.blocks)] = seq.blocks
        K = self._chunk_tokens
        cdata = _np.zeros((1, K), _np.float32)
        cpos = _np.zeros((1, K), _np.float32)
        cstart = _np.zeros((1,), _np.float32)
        clen = _np.zeros((1,), _np.float32)
        ctable = _np.zeros((1, self._table_width), _np.float32)
        if chunk_seq is not None:
            s0 = chunk_seq.n_prefilled
            cdata[0, :chunk_len] = chunk_seq.tokens[s0:s0 + chunk_len]
            cpos[0, :chunk_len] = _np.arange(s0, s0 + chunk_len)
            cstart[0] = s0
            clen[0] = chunk_len
            ctable[0, :len(chunk_seq.blocks)] = chunk_seq.blocks
        with self._step_lock:
            outs, dd = self._dispatch(
                self._exe, "mixed", data=data, positions=pos,
                block_table=table, chunk_data=cdata,
                chunk_positions=cpos, chunk_start=cstart,
                chunk_len=clen, chunk_table=ctable)
            self._commit_caches(outs, base=4)
        self._n_steps += 1
        self._n_step_dispatches += dd
        self._occ_sum += len(active)
        self._cache_occ_sum += self.cache.occupancy
        STEPS.inc()
        if chunk_seq is not None:
            self._advance_chunk(chunk_seq, chunk_len, outs)
        # ONE host copy of the (capacity, vocab) logits per step, shared
        # by every sampling/temperature/collect_logits sequence (rows
        # are per-slot, so a misbehaving user sampler can only touch its
        # own row)
        logits_host = None
        if any(self._needs_logits(s) for _, s in decoding):
            # analyze: ok(hostsync) the step's ONE logits readback, shared by every sampling/temperature slot (documented in the module doc)
            logits_host = outs[0].asnumpy()
        # likewise ONE readback of the greedy-token output for the
        # whole step, not one per active slot
        next_host = None
        if decoding:
            # analyze: ok(hostsync) the greedy-token readback IS the streamed response — the documented one sync per decode iteration
            next_host = outs[1].asnumpy()
        for slot, seq in decoding:
            seq.pos += 1
            self._n_slot_iters += 1
            try:
                tok = self._pick_token(seq, outs, slot, logits_host,
                                       next_host)
            except Exception as exc:   # noqa: BLE001 — user sampler;
                self._finish(seq, error=exc)   # contain to this stream
                continue
            self._n_slot_tokens += 1
            self._emit(seq, tok)
            self._maybe_finish(seq, tok)
        if it_spans:
            for sp in it_spans:
                sp.end()
        if self._watchdog is not None:
            self._watchdog.end()
        STEP_MS.observe((time.perf_counter() - t0) * 1e3)

    def _advance_chunk(self, chunk_seq, chunk_len, outs):
        """Account this iteration's prefill chunk; on the LAST chunk,
        publish sharable full blocks into the prefix trie and emit the
        sequence's first token from the chunk head (outputs base 2)."""
        chunk_seq.n_prefilled += chunk_len
        self._n_prefill_chunks += 1
        PREFILL_CHUNKS.inc()
        if chunk_seq.n_prefilled < chunk_seq.prefill_target:
            return
        # last chunk landed: the chunk head's greedy token (or
        # logits row) is this sequence's FIRST token
        chunk_seq.pos = chunk_seq.prefill_target
        if chunk_seq.prefill_span is not None:
            chunk_seq.prefill_span.end()
            chunk_seq.prefill_span = None
        if self._prefix_cache:
            # publish the finished prefill's FULL blocks for COW reuse
            # (the trie takes its own reference on each; the partial
            # tail block is never shared, so generation writes stay
            # exclusive by construction)
            self.cache.register_prefix(
                chunk_seq.tokens[:chunk_seq.prefill_target],
                chunk_seq.prefill_target, chunk_seq.blocks)
        # per-sequence containment: a bad user sampler must
        # fail ONLY its own stream, never the engine
        try:
            tok = self._pick_token(chunk_seq, outs, 0, base=2)
        except Exception as exc:   # noqa: BLE001
            self._finish(chunk_seq, error=exc)
        else:
            self._emit(chunk_seq, tok)
            self._maybe_finish(chunk_seq, tok)

    def _fork_block(self, seq, idx):
        """COW safety valve: give ``seq`` a private copy of table entry
        ``idx`` when that block is shared.  Device-side row copy (one
        eager op per cache array, never on the steady-state step path:
        full-blocks-only sharing means the engine's writes always land
        past every shared row, so this triggers only through direct
        cache manipulation)."""
        old = seq.blocks[idx]
        new = self.cache.fork_for_write(old)
        if new is None:
            return
        for nd in self._cache_arrs:
            nd._set_data(nd._data.at[new].set(nd._data[old]))
        seq.blocks[idx] = new

    def _step_spec(self, active, chunk_seq=None, chunk_len=0):
        """One draft-verify iteration (docs/DECODE.md): propose up to
        ``spec_k`` tokens per decoding slot, verify every span in ONE
        compiled donated launch of the span step, and commit the
        longest draft prefix that matches the target model's own greedy
        tokens.  Greedy acceptance keeps the stream token-identical to
        non-speculative decoding by construction — draft token j
        commits only when it equals greedy output j-1, so every emitted
        token is the argmax the one-token engine would have produced.
        A rejected tail rolls back by CURSOR arithmetic alone: the next
        span's scatter overwrites rows from the new ``pos`` before its
        gather, and surviving stale rows sit at positions above every
        query's causal mask (rollback math in docs/DECODE.md)."""
        t0 = time.perf_counter()
        if self._watchdog is not None:
            self._watchdog.begin()
        it_spans = None
        if _tracing.enabled():
            it_spans = [
                _tracing.start_span(
                    "decode.iteration",
                    parent=getattr(s.trace_span, "context", None),
                    step=self._n_steps, slot=slot, pos=s.pos)
                for slot, s in active if s.trace_span is not None]
        if chunk_seq is not None and chunk_seq.slot is None:
            chunk_seq, chunk_len = None, 0   # preempted after selection
        decoding = [(slot, seq) for slot, seq in active
                    if seq.n_prefilled >= seq.prefill_target]
        S = self._span
        bs = self.cache.block_size
        vocab = int(self._cfg.get("num_classes", 0))
        data = _np.zeros((self.capacity, S), _np.float32)
        pos = _np.zeros((self.capacity, S), _np.float32)
        sstart = _np.zeros((self.capacity,), _np.float32)
        slen = _np.zeros((self.capacity,), _np.float32)
        table = _np.zeros((self.capacity, self._table_width), _np.float32)
        drafts = {}
        for slot, seq in decoding:
            draft = []
            # budget: the span's rows must fit the context, and tokens
            # past this stream's length stop are wasted verification
            budget = min(self._spec_k,
                         self._max_context - seq.pos - 1,
                         seq.max_new_tokens - seq.n_generated - 1)
            if (budget > 0 and seq.speculative
                    and not self._needs_logits(seq)):
                try:
                    draft = [int(t) for t in
                             self._drafter.propose(seq.tokens, budget)]
                except Exception:   # noqa: BLE001 — a drafter bug costs
                    draft = []      # speedup, never a stream
                draft = [t for t in draft[:budget] if 0 <= t < vocab]
            # opportunistic span-block growth: row seq.pos is already
            # guaranteed by _tick's _ensure_blocks; extra draft rows
            # TRIM on pressure instead of preempting (the `active`
            # snapshot must stay placed through this step)
            L = 1 + len(draft)
            while (seq.pos + L - 1) // bs >= len(seq.blocks):
                try:
                    seq.blocks += self.cache.alloc(1)
                except CacheOOMError:
                    L = min(1 + len(draft),
                            max(1, len(seq.blocks) * bs - seq.pos))
                    draft = draft[:L - 1]
                    break
            # COW guard: fork any shared block the span would write
            for bi in range(seq.pos // bs, (seq.pos + L - 1) // bs + 1):
                if self.cache.ref(seq.blocks[bi]) > 1:
                    self._fork_block(seq, bi)
            drafts[slot] = draft
            data[slot, :L] = [seq.last_token] + draft
            pos[slot, :L] = _np.arange(seq.pos, seq.pos + L)
            sstart[slot] = seq.pos
            slen[slot] = L
            table[slot, :len(seq.blocks)] = seq.blocks
            if draft:
                self._n_spec_proposed += len(draft)
                SPEC_PROPOSED.inc(len(draft))
        K = self._chunk_tokens
        cdata = _np.zeros((1, K), _np.float32)
        cpos = _np.zeros((1, K), _np.float32)
        cstart = _np.zeros((1,), _np.float32)
        clen = _np.zeros((1,), _np.float32)
        ctable = _np.zeros((1, self._table_width), _np.float32)
        if chunk_seq is not None:
            s0 = chunk_seq.n_prefilled
            cdata[0, :chunk_len] = chunk_seq.tokens[s0:s0 + chunk_len]
            cpos[0, :chunk_len] = _np.arange(s0, s0 + chunk_len)
            cstart[0] = s0
            clen[0] = chunk_len
            ctable[0, :len(chunk_seq.blocks)] = chunk_seq.blocks
        with self._step_lock:
            outs, dd = self._dispatch(
                self._exe, "spec", data=data, positions=pos,
                span_start=sstart, span_len=slen, block_table=table,
                chunk_data=cdata, chunk_positions=cpos,
                chunk_start=cstart, chunk_len=clen, chunk_table=ctable)
            self._commit_caches(outs, base=4)
        self._n_steps += 1
        self._n_step_dispatches += dd
        self._occ_sum += len(active)
        self._cache_occ_sum += self.cache.occupancy
        STEPS.inc()
        if chunk_seq is not None:
            self._advance_chunk(chunk_seq, chunk_len, outs)
        # same readback discipline as the mixed step: ONE logits copy
        # shared by every sampling slot, ONE greedy-token copy for the
        # whole step — span rows are (slot * S + j)
        logits_host = None
        if any(self._needs_logits(s) for _, s in decoding):
            # analyze: ok(hostsync) the step's ONE logits readback, shared by every sampling/temperature slot (documented in the module doc)
            logits_host = outs[0].asnumpy()
        next_host = None
        if decoding:
            # analyze: ok(hostsync) the greedy-token readback IS the streamed response — the documented one sync per decode iteration
            next_host = outs[1].asnumpy()
        for slot, seq in decoding:
            draft = drafts.get(slot, [])
            L = 1 + len(draft)
            self._n_slot_iters += 1
            accepted = 0
            for j in range(L):
                # row j is the target's verdict GIVEN span tokens
                # 0..j; it is reached only while every earlier draft
                # token matched the target's greedy choice
                seq.pos += 1
                try:
                    tok = self._pick_token(seq, outs, slot * S + j,
                                           logits_host, next_host)
                except Exception as exc:   # noqa: BLE001 — user
                    self._finish(seq, error=exc)   # sampler: contain
                    break
                self._n_slot_tokens += 1
                if j > 0:
                    accepted += 1
                self._emit(seq, tok)
                self._maybe_finish(seq, tok)
                if seq.slot is None:
                    break                  # finished mid-span
                if j < L - 1 and draft[j] != tok:
                    break                  # tail rejected: cursor stays
            if accepted:
                self._n_spec_accepted += accepted
                SPEC_ACCEPTED.inc(accepted)
            if draft:
                self._spec_window.append((len(draft), accepted))
        if self._n_spec_proposed:
            ACCEPT_RATE.set(self._n_spec_accepted
                            / float(self._n_spec_proposed))
            wp = sum(p for p, _ in self._spec_window)
            if wp:
                ACCEPT_WINDOW.set(
                    sum(a for _, a in self._spec_window) / float(wp))
        if self._n_slot_iters:
            TOKENS_PER_LAUNCH.set(self._n_slot_tokens
                                  / float(self._n_slot_iters))
        if it_spans:
            for sp in it_spans:
                sp.end()
        if self._watchdog is not None:
            self._watchdog.end()
        STEP_MS.observe((time.perf_counter() - t0) * 1e3)

    # ------------------------------------------------------------------
    @staticmethod
    def _needs_logits(seq):
        return (seq.sampler is not None or seq.temperature > 0
                or seq.handle.logits is not None)

    def _pick_token(self, seq, outs, row, logits_host=None, next_host=None,
                    base=0):
        """Greedy reads the on-device argmax output; samplers and
        temperature read the logits row.  Host-side on purpose: the
        readback is the stream, and numpy sampling keeps the device
        program fixed-shape.  ``base`` selects the output pair — 0 for
        the shared decode head, 2 for the chunk head that yields a
        prompt's first token on its final prefill chunk."""
        if self._needs_logits(seq):
            if logits_host is None:
                # analyze: ok(hostsync) chunk-completion readback of the first token's logits (once per admission, not per step)
                logits_host = outs[base].asnumpy()
            logits = logits_host[row]
            if seq.handle.logits is not None:
                # analyze: ok(hostsync) copies an already-host logits row into the user-visible handle
                seq.handle.logits.append(_np.array(logits, copy=True))
            if seq.sampler is not None:
                return int(seq.sampler(logits))
            if seq.temperature > 0:
                z = logits / max(seq.temperature, 1e-6)
                z = z - z.max()
                p = _np.exp(z)
                p /= p.sum()
                return int(seq.rng().choice(len(p), p=p))
            return int(logits.argmax())
        if next_host is None:
            # analyze: ok(hostsync) chunk-completion first-token readback; that token is the stream's first byte
            next_host = outs[base + 1].asnumpy()
        return int(next_host[row])

    def _emit(self, seq, tok):
        now = time.monotonic()
        seq.tokens.append(tok)
        seq.last_token = tok
        if seq.t_first is None:
            seq.t_first = now
            ttft = (now - seq.t_submit) * 1e3
            seq.handle.ttft_ms = ttft
            TTFT_MS.observe(ttft)
            # under _cv: stats() iterates these deques from other threads
            with self._cv:
                self._ttfts.append(ttft)
                if seq.submit_step is not None:
                    steps = self._n_steps - seq.submit_step
                    self._ttft_steps.append(steps)
                    TTFT_STEPS.observe(steps)
        seq.handle._emit(tok)
        self._n_tokens += 1
        TOKENS.inc()

    def _maybe_finish(self, seq, tok):
        if seq.eos_id is not None and tok == seq.eos_id:
            self._finish(seq, reason="eos")
        elif seq.n_generated >= seq.max_new_tokens:
            self._finish(seq, reason="length")
        elif seq.pos >= self._max_context:
            self._finish(seq, reason="context")

    def _finish(self, seq, reason=None, error=None):
        with self._cv:
            self._sched.release(seq)
        if seq.queue_span is not None:       # finished while waiting
            seq.queue_span.end()
            seq.queue_span = None
        if seq.prefill_span is not None:     # finished mid-prefill
            seq.prefill_span.end()
            seq.prefill_span = None
        if seq.trace_span is not None:
            seq.trace_span.end(
                finish_reason=(reason if error is None else "error"),
                error=(type(error).__name__ if error is not None
                       else None),
                tokens=seq.n_generated, preemptions=seq.preemptions)
            seq.trace_span = None
        if error is None and reason == "cancelled":
            self._n_cancelled += 1
            CANCELLED.inc()
        elif error is None:
            self._n_completed += 1
            COMPLETED.inc()
        elif isinstance(error, DeadlineExceededError):
            self._n_expired += 1
            EXPIRED.inc()
        else:
            self._n_failed += 1
            FAILED.inc()
        seq.handle._finish(reason=reason, error=error)

    # ------------------------------------------------------------------
    # weights: hot reload
    # ------------------------------------------------------------------
    def check_params(self, arg_params):
        """Validate a candidate checkpoint against the bound model +
        cache layout (server reload calls this BEFORE touching any
        replica, so a bad checkpoint is a clean 409)."""
        self._check_params(arg_params)

    def swap_params(self, arg_params, aux_params=None, version=None):
        """Hot-swap weights under the step lock: in-flight sequences
        continue on the new weights at the next iteration, the KV cache
        (and therefore every stream) is preserved.  ``version`` (a tag
        or epoch) stamps ``stats()["model_version"]`` atomically with
        the swap.  Raises ``MXNetError`` — without touching anything —
        when shapes don't match."""
        import jax
        from ..ndarray.ndarray import NDArray
        self._check_params(arg_params)
        with self._step_lock:
            for name in self._weight_names:
                v = arg_params[name]
                if not isinstance(v, NDArray):
                    # analyze: ok(hostsync) hot-reload weight staging crosses the host by contract; not on the per-iteration path
                    v = NDArray(_np.asarray(v))
                dst = self._exe.arg_dict[name]
                data = v._data
                if data.dtype != dst._data.dtype:
                    data = data.astype(dst._data.dtype)
                # re-shard onto the destination's bind-time placement:
                # under a TP mesh (mx.fleet) params carry NamedShardings
                # that a plain single-device put would clobber.
                dst._set_data(jax.device_put(data, dst._data.sharding))
            if version is not None:
                self._model_version = version
        if self._prefix_cache:
            # the trie's cached rows were computed under the replaced
            # weights; the engine thread flushes at its next tick (the
            # cache stays single-owner).  An engine that never started
            # has no owner thread — flush inline.
            with self._step_lock:
                self._prefix_flush = self._thread is not None
            if self._thread is None:
                self.cache.flush_prefixes()
        RELOADS.inc()

    def reload(self, prefix, tag=None, epoch=None):
        """Load an mx.checkpoint (``tag``/newest) or legacy
        ``prefix-%04d.params`` (``epoch``) and hot-swap (docs/DECODE.md
        + docs/CHECKPOINT.md)."""
        from ..checkpoint import resolve_params
        arg_params, _aux, version = resolve_params(
            prefix, tag, epoch, what="decode reload")
        self.swap_params(arg_params, version=version)
        return version

    # ------------------------------------------------------------------
    def drain(self, timeout=None):
        """Wait until all submitted work has settled."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cv:
                idle = (not self._sched.waiting
                        and not self._sched.has_active()
                        and not self._mid_admission)
            if idle:
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)

    def stop(self, drain=True, timeout=None):
        """Stop the engine; ``drain=True`` finishes queued work first,
        ``drain=False`` fails it with ``ServerClosedError``."""
        with self._cv:
            self._closing = True
            if not drain:
                self._abort = True
            self._cv.notify_all()
        if self._watchdog is not None:
            self._watchdog.disarm()
        if self._thread is not None:
            self._thread.join(timeout)
            # a timed-out join leaves the loop running: keep _thread so
            # start() can't spawn a SECOND loop over the same slots
            if not self._thread.is_alive():
                self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------------
    def stats(self):
        """Operational snapshot (glossary in docs/DECODE.md)."""
        with self._cv:
            depth = len(self._sched.waiting)
            active = sum(1 for s in self._sched.slots if s is not None)
            ttfts = sorted(self._ttfts)
            ttft_steps = sorted(self._ttft_steps)
        p99 = _percentile(ttfts, 0.99)
        steps_p99 = _percentile(ttft_steps, 0.99)
        return {
            "capacity": self.capacity,
            "queue_depth": depth,
            "active_sequences": active,
            "admitted": self._n_admitted,
            "completed": self._n_completed,
            "failed": self._n_failed,
            "expired": self._n_expired,
            "cancelled": self._n_cancelled,
            "tokens_generated": self._n_tokens,
            "steps": self._n_steps,
            "prefills": self._n_prefills,
            "preemptions": self._n_preemptions,
            "mean_slot_occupancy": (self._occ_sum / self._n_steps
                                    if self._n_steps else None),
            "mean_cache_occupancy": (self._cache_occ_sum / self._n_steps
                                     if self._n_steps else None),
            "steady_state_retraces": self._steady_retraces,
            "decode_step_dispatches": self._n_step_dispatches,
            "dispatches_per_step": (self._n_step_dispatches / self._n_steps
                                    if self._n_steps else None),
            "prefill_chunks": self._n_prefill_chunks,
            "prefill_chunks_per_iter": (self._n_prefill_chunks
                                        / self._n_steps
                                        if self._n_steps else None),
            "chunk_tokens": self._chunk_tokens,
            "ttft_p99_ms": p99,
            "ttft_steps_p99": steps_p99,
            "model_version": self._model_version,
            "attn_impl": _paged_attn_impl(),
            "cache_donation": self._donate,
            "spec_k": self._spec_k,
            "spec_impl": self._spec_impl,
            "spec_proposed": self._n_spec_proposed,
            "spec_accepted": self._n_spec_accepted,
            "accept_rate": (self._n_spec_accepted
                            / self._n_spec_proposed
                            if self._n_spec_proposed else None),
            "accept_rate_window": (
                sum(a for _, a in self._spec_window)
                / float(sum(p for p, _ in self._spec_window))
                if sum(p for p, _ in self._spec_window) else None),
            "tokens_per_launch": (self._n_slot_tokens
                                  / self._n_slot_iters
                                  if self._n_slot_iters else None),
            "cache": {
                "num_blocks": self.cache.num_blocks,
                "block_size": self.cache.block_size,
                "blocks_used": self.cache.used_count,
                "blocks_free": self.cache.free_count,
                "occupancy": round(self.cache.occupancy, 4),
                "prefix_sharing": self._prefix_cache,
                "prefix_hit_blocks":
                    self.cache.prefix_stats["hit_blocks"],
                "prefix_trie_blocks":
                    self.cache.prefix_stats["trie_blocks"],
            },
        }
