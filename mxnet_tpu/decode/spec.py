"""mx.speculative — draft proposers for draft-verify decoding.

Speculative decoding (Leviathan et al., 2023) splits each serving
iteration into a cheap PROPOSE and an exact VERIFY: a drafter guesses
the next K tokens of a stream, the target model scores all K+1
positions in one launch, and the longest prefix of the draft that
matches the target's own greedy choices is committed.  Under greedy
acceptance every emitted token is *by construction* a token the target
model would have produced one-at-a-time — speculation changes tokens
per launch, never the stream (docs/DECODE.md, "Speculative decoding &
prefix sharing").

This module is the PROPOSE half.  The VERIFY half is the engine's
spec step (``engine.DecodeEngine._step_spec``), which rides the same
chunk-attention primitive as chunked prefill
(``_contrib_PagedChunkPrefillAttention`` — a span of new tokens
attending a live paged cache with per-row starts) batched across all
slots, so verification costs ONE compiled donated launch per iteration
exactly like plain decoding.

Two drafters ship:

* :class:`NGramDrafter` (default) — self-speculative prompt lookup
  (Saxena, 2023): match the stream's trailing n-gram against its own
  earlier tokens and propose the historical continuation.  Zero extra
  launches, zero extra weights; shines exactly where serving is
  repetitive (summarization, code edit, RAG quoting its context).
  A miss proposes nothing and the iteration degrades to plain
  one-token decoding — never worse than baseline launches.
* :class:`DraftModelDrafter` — a small draft transformer loaded
  through the ordinary checkpoint machinery (same weight-name
  contract as the target).  Proposes a whole K-token span with ONE
  compiled launch of an unrolled draft program
  (``get_draft_span_symbol``), so draft mode adds exactly one launch
  per iteration outside the engine's one-launch witness — worth it
  when the draft model is much cheaper than the target and acceptance
  is high.  Tier-1 pins the mechanism, not the economics.

Implementation selection follows the kernel-knob contract of
``pallas.dispatch.choose_impl`` (``MXNET_DECODE_SPEC_IMPL`` =
``auto|ngram|draft|off``): ``auto`` picks the draft model when a
checkpoint was provided and n-gram otherwise; forcing ``draft``
without a checkpoint raises instead of silently measuring the wrong
path; a draft model that fails to load under ``auto`` falls back to
n-gram, bumps ``decode_spec_fallbacks`` and leaves a flight-recorder
note (``spec_drafter_fallback``).
"""
from __future__ import annotations

import numpy as _np

from ..telemetry import REGISTRY
from ..telemetry.flight import RECORDER

__all__ = ["Drafter", "NGramDrafter", "DraftModelDrafter",
           "choose_spec_impl", "make_drafter"]

SPEC_PROPOSED = REGISTRY.counter(
    "decode_spec_proposed", "draft tokens proposed for verification")
SPEC_ACCEPTED = REGISTRY.counter(
    "decode_spec_accepted", "draft tokens accepted by target-model "
    "verification (committed to streams)")
SPEC_FALLBACKS = REGISTRY.counter(
    "decode_spec_fallbacks", "auto-mode draft-model selections that "
    "fell back to the n-gram drafter, labeled by `reason`")
ACCEPT_RATE = REGISTRY.gauge(
    "decode_accept_rate", "accepted/proposed draft-token ratio over "
    "the engine's lifetime", unit="ratio")
TOKENS_PER_LAUNCH = REGISTRY.gauge(
    "decode_tokens_per_launch", "tokens committed per compiled decode "
    "launch (1.0 = non-speculative)", unit="tokens")


def choose_spec_impl(impl, has_draft_model, *, env_var="MXNET_DECODE_SPEC_IMPL"):
    """Resolve the drafter implementation knob.

    ``impl`` is the raw knob value (the CALLER reads the env var with a
    literal name so the envknobs analyze pass sees the site); returns
    ``"ngram"``, ``"draft"`` or ``None`` (speculation off).  Mirrors
    ``pallas.dispatch.choose_impl``: forcing ``draft`` without a draft
    checkpoint raises — never silently measure the wrong path.
    """
    if impl == "off":
        return None
    if impl not in ("auto", "ngram", "draft"):
        raise ValueError("%s=%s; use auto|ngram|draft|off"
                         % (env_var, impl))
    if impl == "draft":
        if not has_draft_model:
            raise ValueError(
                "%s=draft but no draft checkpoint was provided "
                "(DecodeEngine(draft_params=..., draft_config=...))"
                % env_var)
        return "draft"
    if impl == "ngram":
        return "ngram"
    return "draft" if has_draft_model else "ngram"


class Drafter:
    """Proposer interface: ``propose(tokens, k)`` returns up to ``k``
    guessed continuation ids for a stream whose full history (prompt +
    generated) is ``tokens``.  Proposals are *hints* — the verify step
    accepts only the prefix that matches the target model's own greedy
    argmax, so a bad drafter costs speedup, never correctness."""

    name = "null"

    def propose(self, tokens, k):
        return []


class NGramDrafter(Drafter):
    """Self-speculative prompt lookup: find the most recent earlier
    occurrence of the stream's trailing n-gram (longest ``n`` in
    ``[min_n, max_n]`` wins) and propose the tokens that followed it.

    Pure host-side integer matching — no device work, no extra
    weights, and no second tokenizer contract.  Window-bounded so a
    very long stream costs O(window) per proposal, not O(history).
    """

    name = "ngram"

    def __init__(self, max_n=3, min_n=1, window=1024):
        if not (1 <= int(min_n) <= int(max_n)):
            raise ValueError("NGramDrafter: need 1 <= min_n <= max_n")
        self.max_n = int(max_n)
        self.min_n = int(min_n)
        self.window = int(window)

    def propose(self, tokens, k):
        k = int(k)
        hist = [int(t) for t in tokens[-self.window:]]
        n_hist = len(hist)
        for n in range(min(self.max_n, n_hist - 1), self.min_n - 1, -1):
            tail = hist[n_hist - n:]
            # most recent earlier occurrence wins: recency beats length
            # ties at a given n, and longer n is tried first
            for i in range(n_hist - n - 1, -1, -1):
                if hist[i:i + n] == tail:
                    cont = hist[i + n:i + n + k]
                    if cont:
                        return cont
        return []


class DraftModelDrafter(Drafter):
    """Draft-transformer proposer: a (small) checkpoint bound through
    ``models.transformer.get_draft_span_symbol`` — the K-step greedy
    draft loop UNROLLED into one compiled program, so a proposal costs
    exactly ONE draft-net dispatch and one K-int readback whatever K
    is (the PR 16 stretch fix: the sequential form cost K launches +
    K readbacks per span, which ate the speculative win for any
    non-trivial draft model).

    The program is bound lazily per span length K (the engine always
    proposes at its fixed ``spec_k``, so in practice ONE bind) at the
    fixed ``(1, seq_len)`` geometry — one compile, zero steady-state
    retraces; history is left-aligned and zero-padded, trimmed to
    ``seq_len - K`` context tokens so every unrolled write stays in
    range, and causal masking makes the padded tail invisible to every
    row that is read.  The launch is still OUTSIDE the engine's
    one-launch-per-iteration witness, which covers the target model's
    verify step only — it is pinned by its own dispatch-count witness
    (tests/test_decode.py).
    """

    name = "draft"

    def __init__(self, arg_params, model_config, ctx=None):
        from ..context import current_context
        from ..models import transformer

        self._cfg = dict(model_config)
        self._cfg.pop("dropout", None)
        self._seq_len = int(self._cfg.get("seq_len", 1024))
        self._ctx = ctx if ctx is not None else current_context()
        self._tf = transformer
        # weight names are K-independent: validate the checkpoint NOW
        # (make_drafter's auto-fallback contract keys on construction
        # failure), bind per-K programs lazily in propose()
        probe = transformer.get_draft_span_symbol(1, **self._cfg)
        self._want = set(probe.list_arguments()) - {"data", "length",
                                                    "iota"}
        missing = [n for n in sorted(self._want) if n not in arg_params]
        if missing:
            raise ValueError("draft checkpoint missing params: %s"
                             % ", ".join(missing[:4]))
        self._params = {k: arg_params[k] for k in self._want}
        self._exes = {}                    # span K -> bound executor
        self._iota = _np.arange(self._seq_len,
                                dtype=_np.float32).reshape(1, -1)

    def _span_exe(self, k):
        exe = self._exes.get(k)
        if exe is None:
            from ..ndarray.ndarray import NDArray
            dsym = self._tf.get_draft_span_symbol(k, **self._cfg)
            shapes = {"data": (1, self._seq_len), "length": (1,)}
            if "iota" in dsym.list_arguments():   # absent when K == 1
                shapes["iota"] = (1, self._seq_len)
            exe = dsym.simple_bind(ctx=self._ctx, grad_req="null",
                                   **shapes)
            staged = {}
            for n, v in self._params.items():
                if not isinstance(v, NDArray):
                    # analyze: ok(hostsync) draft checkpoint staged host->device once at the first K-span bind, not on the serving step path
                    v = NDArray(_np.asarray(v))
                staged[n] = v
            exe.copy_params_from(staged, {}, allow_extra_params=True)
            self._exes[k] = exe
        return exe

    def propose(self, tokens, k):
        k = int(k)
        if k < 1 or k >= self._seq_len:
            return []
        ctx_toks = [int(t) for t in tokens][-(self._seq_len - k):]
        n = len(ctx_toks)
        if n == 0:
            return []
        data = _np.zeros((1, self._seq_len), _np.float32)
        data[0, :n] = ctx_toks
        exe = self._span_exe(k)
        feeds = {"data": data, "length": _np.array([n], _np.float32)}
        if "iota" in exe.arg_dict:        # K=1 unrolls no writeback
            feeds["iota"] = self._iota
        out = exe.forward(is_train=False, **feeds)[0]
        # analyze: ok(hostsync) the K-token readback IS the drafter's output — one host sync per span, not per token
        return [int(t) for t in out.asnumpy().reshape(-1)[:k]]


def make_drafter(impl, draft_params=None, draft_config=None, ctx=None,
                 forced=False):
    """Instantiate the resolved drafter.  Under ``auto``
    (``forced=False``) a draft checkpoint that fails to load degrades
    to the n-gram drafter (counter + flight-recorder note) instead of
    killing the engine; a FORCED draft model propagates the error —
    the three-knob contract (never silently measure the wrong path)."""
    if impl is None:
        return None
    if impl == "ngram":
        return NGramDrafter()
    try:
        return DraftModelDrafter(draft_params, draft_config, ctx=ctx)
    except Exception as exc:
        if forced:
            raise
        SPEC_FALLBACKS.labels(reason="load_error").inc()
        RECORDER.note("spec_drafter_fallback", error=str(exc)[:200])
        return NGramDrafter()
