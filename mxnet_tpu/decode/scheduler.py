"""Continuous-batching scheduler state (host-side policy, no device code).

Iteration-level scheduling in the Orca (OSDI '22) sense: the unit of
work is one *decode iteration* over a fixed array of batch slots, and
sequences join/leave between iterations — a new request never waits for
the batch to drain, a finished request never pads it.  The policies are
deliberately simple and documented (docs/DECODE.md):

* **Admission** — FIFO, no head-of-line bypass: the oldest waiting
  sequence is admitted as soon as a slot AND its FIRST prefill chunk's
  cache blocks are free (chunked prefill grows the rest incrementally,
  one chunk per decode iteration — Sarathi-style stall-free prefill).
  ``admission='static'`` degrades to run-to-completion batching (admit
  only into an idle engine) — kept as the measured A/B baseline for
  ``bench.py --mode decode``.
* **Preemption** — on cache pressure the YOUNGEST running sequence is
  preempted *by recompute*: its blocks are freed, its tokens so far
  fold into a new prompt, and it rejoins the FRONT of the wait queue,
  re-prefilling when memory frees up.  Streamed tokens are never
  re-emitted.
* **Expiry** — deadlines are checked while waiting and between
  iterations; an expired sequence settles with
  ``DeadlineExceededError`` exactly like a serving request.

Everything here is plain-Python and single-owner: only the engine
thread mutates slots/blocks, only ``submit`` (any thread, under the
engine lock) appends to the wait queue — which is what makes the
policy unit-testable without a device.
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque

from ..serving.batcher import DeadlineExceededError, QueueFullError

__all__ = ["Sequence", "StreamHandle", "Scheduler",
           "DeadlineExceededError", "QueueFullError"]


class StreamHandle:
    """Client-side view of one generation: an iterator of streamed
    tokens plus a synchronous :meth:`result`.

    The engine appends every generated token to :attr:`tokens` *before*
    publishing it to the event queue, so ``tokens`` is always a prefix-
    consistent transcript; iteration consumes the queue.  ``ttft_ms``
    is set at the first token (time-to-first-token, queue wait
    included)."""

    def __init__(self, rid):
        self.rid = rid
        self.tokens = []
        self.logits = None          # populated when collect_logits=True
        self.finish_reason = None
        self.error = None
        self.ttft_ms = None
        self.preemptions = 0
        self._events = _queue.Queue()
        self._done = threading.Event()
        self._cancelled = threading.Event()

    def cancel(self):
        """Ask the engine to stop this generation (client went away).
        Takes effect at the next scheduler iteration: the sequence's
        slot and cache blocks are released and the stream settles with
        ``finish_reason='cancelled'``.  Idempotent; a no-op once done."""
        self._cancelled.set()

    def cancelled(self):
        return self._cancelled.is_set()

    # engine side ------------------------------------------------------
    def _emit(self, token):
        self.tokens.append(token)
        self._events.put(("token", token))

    def _finish(self, reason=None, error=None):
        self.finish_reason = reason if error is None else "error"
        self.error = error
        self._events.put(("done", reason) if error is None
                         else ("error", error))
        self._done.set()

    # client side ------------------------------------------------------
    def __iter__(self):
        while True:
            kind, payload = self._events.get()
            if kind == "token":
                yield payload
            elif kind == "done":
                return
            else:
                raise payload

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Wait for completion; returns the generated tokens (prompt
        excluded).  Raises the stream's error (deadline, cache OOM,
        server closed) if it failed."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation %d still running" % self.rid)
        if self.error is not None:
            raise self.error
        return list(self.tokens)


class Sequence:
    """One request's full scheduler state."""

    def __init__(self, rid, prompt, max_new_tokens, eos_id=None,
                 deadline=None, temperature=0.0, sampler=None, seed=None,
                 collect_logits=False, speculative=True):
        self.rid = rid
        self.tokens = list(int(t) for t in prompt)   # prompt + generated
        self.n_prompt = len(self.tokens)             # original prompt size
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.deadline = deadline
        self.temperature = float(temperature)
        self.sampler = sampler
        self.seed = seed
        self.handle = StreamHandle(rid)
        if collect_logits:
            self.handle.logits = []
        # per-request speculative opt-out (docs/DECODE.md): False pins
        # this stream to one verified token per iteration even on a
        # spec-enabled engine.  Sampling/temperature/collect_logits
        # streams are excluded from drafting automatically either way —
        # greedy acceptance is exact only for greedy streams.
        self.speculative = bool(speculative)
        self._rng = None
        # engine-owned placement state
        self.slot = None
        self.blocks = []
        self.pos = 0              # next cache position to be written
        self.last_token = None    # token the next decode step consumes
        # chunked-prefill cursor: prompt rows [0, n_prefilled) are in
        # the KV cache; the sequence decodes once n_prefilled reaches
        # prefill_target (set at admission to the full prompt length)
        self.prefill_target = 0
        self.n_prefilled = 0
        self.t_submit = time.monotonic()
        self.t_first = None
        self.submit_step = None   # engine step count at submit (TTFT-steps)
        self.preemptions = 0
        # mx.trace spans (None when tracing is off): trace_span covers
        # submit -> finish, queue_span covers submit -> admission,
        # prefill_span covers admission -> last chunk landed
        self.trace_span = None
        self.queue_span = None
        self.prefill_span = None

    @property
    def n_generated(self):
        return len(self.tokens) - self.n_prompt

    @property
    def recompute_prompt(self):
        """Prompt for (re-)prefill: everything produced so far."""
        return self.tokens

    def rng(self):
        if self._rng is None:
            import numpy as np
            self._rng = np.random.RandomState(
                self.seed if self.seed is not None else (self.rid * 9973 + 7))
        return self._rng

    def expired(self, now=None):
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                > self.deadline)


class Scheduler:
    """Slot/queue bookkeeping for the engine (module docstring)."""

    def __init__(self, capacity, cache, max_waiting=256,
                 admission="continuous"):
        if admission not in ("continuous", "static"):
            raise ValueError("admission=%r; use 'continuous' or 'static'"
                             % (admission,))
        self.capacity = int(capacity)
        self.cache = cache
        self.max_waiting = int(max_waiting)
        self.admission = admission
        self.waiting = deque()
        self.slots = [None] * self.capacity

    # -- queue side (called under the engine lock) ---------------------
    def enqueue(self, seq, front=False):
        if len(self.waiting) >= self.max_waiting:
            raise QueueFullError(
                "decode wait queue full (%d sequences)" % self.max_waiting)
        (self.waiting.appendleft if front else self.waiting.append)(seq)

    def take_expired_waiting(self, now=None):
        now = time.monotonic() if now is None else now
        expired = [s for s in self.waiting if s.expired(now)]
        if expired:
            self.waiting = deque(s for s in self.waiting
                                 if not s.expired(now))
        return expired

    # -- slot side (engine thread only) --------------------------------
    def has_active(self):
        return any(s is not None for s in self.slots)

    def active(self):
        """[(slot_index, Sequence)] for occupied slots, slot order."""
        return [(i, s) for i, s in enumerate(self.slots) if s is not None]

    def free_slot(self):
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def may_admit(self, batch_open=False):
        """Admission policy gate: continuous admits into in-flight
        iterations; static only fills an idle engine — ``batch_open``
        is True while the current admission round started from idle, so
        a static batch fills every slot before running to completion."""
        if self.free_slot() is None or not self.waiting:
            return False
        if (self.admission == "static" and self.has_active()
                and not batch_open):
            return False
        return True

    def place(self, seq, slot):
        assert self.slots[slot] is None
        self.slots[slot] = seq
        seq.slot = slot

    def release(self, seq):
        """Recycle the sequence's slot and cache blocks."""
        if seq.slot is not None:
            self.slots[seq.slot] = None
            seq.slot = None
        if seq.blocks:
            self.cache.free(seq.blocks)
            seq.blocks = []

    def pick_prefilling(self):
        """Chunk policy: the OLDEST placed sequence still mid-prefill
        (smallest rid) feeds this iteration's chunk rows — FIFO TTFT
        order, one chunk per iteration."""
        cands = [s for _, s in self.active()
                 if s.n_prefilled < s.prefill_target]
        return min(cands, key=lambda s: s.rid) if cands else None

    def pick_victim(self, exclude=()):
        """Preemption policy: youngest running sequence (largest rid)
        not in ``exclude`` — it has the least recompute to lose and the
        oldest requests keep their latency."""
        cands = [s for _, s in self.active()
                 if s is not None and s not in exclude]
        return max(cands, key=lambda s: s.rid) if cands else None

    def preempt(self, seq):
        """Preempt-by-recompute: free everything, rejoin the queue
        front.  The caller streams nothing; already-emitted tokens stay
        emitted and the re-prefill continues from ``seq.tokens``."""
        self.release(seq)
        seq.pos = 0
        seq.last_token = None
        # a partially-prefilled prompt folds whole: the next admission
        # re-targets the full (prompt + generated) token list
        seq.prefill_target = 0
        seq.n_prefilled = 0
        seq.preemptions += 1
        seq.handle.preemptions = seq.preemptions
        self.waiting.appendleft(seq)
