"""mx.decode — generative serving: paged KV cache + continuous batching.

The decode engine turns the framework's decoder-only transformer
(``models/transformer.py``) into a *generative* serving workload —
the capability mx.serving's independent-forward batching cannot
express.  The shape is the canonical one (Orca OSDI '22 iteration-level
scheduling; vLLM/PagedAttention SOSP '23 block-paged KV memory),
adapted to this repo's compiled-executor discipline: one fixed-shape
jitted decode step per iteration, zero steady-state retraces, all
sequence raggedness carried in runtime arrays.

Quickstart::

    import mxnet_tpu as mx
    from mxnet_tpu.decode import DecodeEngine

    cfg = dict(num_classes=32000, num_layers=12, d_model=2048,
               num_heads=16, seq_len=1024)          # the training config
    eng = DecodeEngine(arg_params, cfg, capacity=8,
                       block_size=16, num_blocks=256)
    handle = eng.submit(prompt_ids, max_new_tokens=128, eos_id=2)
    for tok in handle:                               # streamed
        ...
    eng.stats()                                      # occupancy, ttft, ...
    eng.stop()

HTTP streaming rides the existing serving stack: pass
``ModelServer(..., decode_engine=eng)`` and ``POST /generate`` streams
chunked JSON-lines (docs/DECODE.md, docs/SERVING.md).
"""
from .cache import CacheOOMError, PagedKVCache
from .engine import DecodeEngine
from .scheduler import (DeadlineExceededError, QueueFullError, Scheduler,
                        Sequence, StreamHandle)
from .spec import (DraftModelDrafter, Drafter, NGramDrafter,
                   choose_spec_impl)

__all__ = ["DecodeEngine", "PagedKVCache", "CacheOOMError", "Scheduler",
           "Sequence", "StreamHandle", "DeadlineExceededError",
           "QueueFullError", "Drafter", "NGramDrafter",
           "DraftModelDrafter", "choose_spec_impl"]
