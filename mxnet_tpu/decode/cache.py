"""Paged KV-cache: fixed-size device blocks + a host-side free list.

The device side is dumb on purpose: per layer, one K and one V array of
shape ``(num_blocks, block_size, num_heads, head_dim)`` bound into the
decode/prefill executors, addressed entirely through runtime block
tables (``ops.nn.paged_decode_attention``).  All *policy* — which
sequence owns which blocks, when to grow, when to evict — lives here on
the host, where it costs integer bookkeeping instead of device
launches.  This is the PagedAttention split (vLLM, SOSP '23): block
tables turn the cache into virtual memory, so ragged sequences share
one fixed-shape compiled step and fragmentation is impossible by
construction (any free block serves any sequence).

Accounting plugs into the PR 4 HBM census: the cache arrays register as
the ``kv_cache`` group of ``telemetry.memory_snapshot()``, and the
``decode_cache_*`` gauges track the free list in real time
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import weakref

from ..base import MXNetError
from ..telemetry import REGISTRY

__all__ = ["CacheOOMError", "PagedKVCache"]

BLOCKS_USED = REGISTRY.gauge(
    "decode_cache_blocks_used", "KV-cache blocks currently allocated",
    unit="blocks")
BLOCKS_FREE = REGISTRY.gauge(
    "decode_cache_blocks_free", "KV-cache blocks on the free list",
    unit="blocks")
CACHE_OCCUPANCY = REGISTRY.gauge(
    "decode_cache_occupancy", "allocated fraction of the KV cache (0..1)",
    unit="ratio")
CACHE_BYTES = REGISTRY.gauge(
    "decode_cache_bytes", "device bytes reserved for the paged KV cache",
    unit="bytes")

# every live allocator contributes to the ONE set of process-wide
# gauges / census group — a second engine in the same process must add
# to the accounting, not clobber the first's
_LIVE = weakref.WeakSet()


def _census_provider():
    _refresh_bytes()          # collected engines stop counting here too
    bufs = []
    for cache in list(_LIVE):
        bufs += [nd._data for nd in getattr(cache, "_arrays", ())]
    return bufs


def _refresh_bytes():
    total = 0
    for cache in list(_LIVE):
        for nd in getattr(cache, "_arrays", ()):
            try:
                total += int(nd._data.nbytes)
            except Exception:
                pass
    CACHE_BYTES.set(total)


class CacheOOMError(MXNetError):
    """The free list cannot satisfy an allocation (after any eviction
    the caller was willing to do)."""


class PagedKVCache:
    """Free-list allocator over ``num_blocks`` cache blocks.

    Pure host state; the engine owns the device arrays and registers
    them via :meth:`attach_arrays`.  Allocation is LIFO (hot blocks
    stay hot), a ``free()`` of a block not currently allocated raises —
    a double free would let two sequences share a block and silently
    corrupt each other's context.
    """

    def __init__(self, num_blocks, block_size):
        if num_blocks <= 0 or block_size <= 0:
            raise MXNetError("PagedKVCache needs positive num_blocks/"
                             "block_size (got %s, %s)"
                             % (num_blocks, block_size))
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._allocated = set()
        _LIVE.add(self)
        self._update_gauges()

    # -- sizing --------------------------------------------------------
    def blocks_for(self, n_tokens):
        """Blocks needed to hold ``n_tokens`` cache rows."""
        return -(-max(int(n_tokens), 0) // self.block_size)

    def blocks_missing(self, have, n_tokens):
        """Blocks a sequence holding ``have`` blocks still needs to
        reach ``n_tokens`` cache rows — the incremental allocation unit
        of chunked prefill, where the table grows chunk by chunk
        instead of whole-prompt at admission."""
        return max(self.blocks_for(n_tokens) - int(have), 0)

    @property
    def free_count(self):
        return len(self._free)

    @property
    def used_count(self):
        return len(self._allocated)

    @property
    def occupancy(self):
        return len(self._allocated) / float(self.num_blocks)

    # -- alloc/free ----------------------------------------------------
    def alloc(self, n):
        """Take ``n`` blocks off the free list (all-or-nothing)."""
        n = int(n)
        if n < 0:
            raise MXNetError("alloc(%d): negative block count" % n)
        if n > len(self._free):
            raise CacheOOMError(
                "KV cache exhausted: need %d blocks, %d free of %d"
                % (n, len(self._free), self.num_blocks))
        out = [self._free.pop() for _ in range(n)]
        self._allocated.update(out)
        self._update_gauges()
        return out

    def free(self, blocks):
        for b in blocks:
            if b not in self._allocated:
                raise MXNetError(
                    "free(%r): block not allocated (double free would "
                    "alias two sequences onto one block)" % (b,))
            self._allocated.discard(b)
            self._free.append(b)
        self._update_gauges()

    def _update_gauges(self):
        used = free = total = 0
        for cache in list(_LIVE):
            used += len(cache._allocated)
            free += len(cache._free)
            total += cache.num_blocks
        BLOCKS_USED.set(used)
        BLOCKS_FREE.set(free)
        CACHE_OCCUPANCY.set(used / float(total) if total else 0.0)

    # -- HBM census ----------------------------------------------------
    def attach_arrays(self, ndarrays):
        """Register the engine's cache NDArrays as the ``kv_cache``
        group of the HBM census (weakly — a collected engine stops
        contributing)."""
        from ..telemetry import memory as _mem
        self._arrays = list(ndarrays)
        _refresh_bytes()
        _mem.track_group("kv_cache", _census_provider)
