"""Paged KV-cache: fixed-size device blocks + a host-side free list.

The device side is dumb on purpose: per layer, one K and one V array of
shape ``(num_blocks, block_size, num_heads, head_dim)`` bound into the
decode/prefill executors, addressed entirely through runtime block
tables (``ops.nn.paged_decode_attention``).  All *policy* — which
sequence owns which blocks, when to grow, when to evict — lives here on
the host, where it costs integer bookkeeping instead of device
launches.  This is the PagedAttention split (vLLM, SOSP '23): block
tables turn the cache into virtual memory, so ragged sequences share
one fixed-shape compiled step and fragmentation is impossible by
construction (any free block serves any sequence).

Copy-on-write prefix sharing (vLLM §4.4, docs/DECODE.md): every
allocated block carries a refcount, ``free()`` is a *decref* (the block
returns to the free list only at zero), and a block-granular prefix
trie keyed on token-block content lets identical prompt prefixes share
their already-prefilled blocks across sequences — ``acquire_prefix``
increfs the matched chain at admission, ``register_prefix`` publishes a
finished prefill's full blocks (the trie holds its own reference, so
the prefix outlives its first sequence), and allocation pressure
evicts trie-only blocks leaf-first before declaring OOM.  Shared
blocks are read-only by construction (only *full* blocks strictly
below the prompt tail are ever shared); ``fork_for_write`` is the
safety valve that gives a writer a private copy of a block whose
refcount is above one.

Accounting plugs into the PR 4 HBM census: the cache arrays register as
the ``kv_cache`` group of ``telemetry.memory_snapshot()`` (device bytes
are per-array, so shared blocks are inherently counted once), and the
``decode_cache_*`` gauges track the free list in real time — a shared
block counts as ONE used block no matter how many sequences reference
it, which is exactly the dedup ``decode_cache_occupancy`` should show
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import weakref

from ..base import MXNetError
from ..telemetry import REGISTRY

__all__ = ["CacheOOMError", "PagedKVCache"]

BLOCKS_USED = REGISTRY.gauge(
    "decode_cache_blocks_used", "KV-cache blocks currently allocated",
    unit="blocks")
BLOCKS_FREE = REGISTRY.gauge(
    "decode_cache_blocks_free", "KV-cache blocks on the free list",
    unit="blocks")
CACHE_OCCUPANCY = REGISTRY.gauge(
    "decode_cache_occupancy", "allocated fraction of the KV cache (0..1)",
    unit="ratio")
CACHE_BYTES = REGISTRY.gauge(
    "decode_cache_bytes", "device bytes reserved for the paged KV cache",
    unit="bytes")
PREFIX_HIT_BLOCKS = REGISTRY.gauge(
    "decode_prefix_hit_blocks", "cumulative KV-cache blocks served from "
    "the shared-prefix trie instead of being re-prefilled",
    unit="blocks")
PREFIX_EVICTIONS = REGISTRY.counter(
    "decode_prefix_evictions", "trie-only prefix blocks evicted "
    "leaf-first under allocation pressure (fleet routing replays make "
    "this routine — invisible eviction churn is a routing-policy bug)")

# every live allocator contributes to the ONE set of process-wide
# gauges / census group — a second engine in the same process must add
# to the accounting, not clobber the first's
_LIVE = weakref.WeakSet()


def _census_provider():
    _refresh_bytes()          # collected engines stop counting here too
    bufs = []
    for cache in list(_LIVE):
        bufs += [nd._data for nd in getattr(cache, "_arrays", ())]
    return bufs


def _refresh_bytes():
    total = 0
    for cache in list(_LIVE):
        for nd in getattr(cache, "_arrays", ()):
            try:
                total += int(nd._data.nbytes)
            except Exception:
                pass
    CACHE_BYTES.set(total)


class CacheOOMError(MXNetError):
    """The free list cannot satisfy an allocation (after any eviction
    the caller was willing to do)."""


class PagedKVCache:
    """Free-list allocator over ``num_blocks`` cache blocks.

    Pure host state; the engine owns the device arrays and registers
    them via :meth:`attach_arrays`.  Allocation is LIFO (hot blocks
    stay hot).  Every block carries a refcount: ``alloc`` hands it out
    at refcount 1, ``free()`` is a decref — the block returns to the
    free list only when the count reaches zero, so a preempted/expired
    sharer can never yank a block its co-sharers (or the prefix trie)
    still reference.  A ``free()`` of a block not currently allocated
    still raises — a true double free would let two sequences share a
    block and silently corrupt each other's context — and the decref
    path keeps an explicit below-zero guard.

    ``prefix_sharing=True`` arms the copy-on-write prefix trie
    (module docstring); off (the default) the allocator behaves exactly
    like the exclusive-ownership original.
    """

    def __init__(self, num_blocks, block_size, prefix_sharing=False):
        if num_blocks <= 0 or block_size <= 0:
            raise MXNetError("PagedKVCache needs positive num_blocks/"
                             "block_size (got %s, %s)"
                             % (num_blocks, block_size))
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.prefix_sharing = bool(prefix_sharing)
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._allocated = set()
        self._ref = {}                 # block id -> refcount (>= 1)
        # prefix trie: nested nodes keyed by the tuple of one block's
        # tokens — node = {"block": id, "children": {tokens: node}}.
        # The trie itself holds one reference on every published block.
        self._prefix_root = {}
        self._prefix_blocks = 0        # blocks currently held by the trie
        self._prefix_hits = 0          # cumulative blocks served shared
        _LIVE.add(self)
        self._update_gauges()

    # -- sizing --------------------------------------------------------
    def blocks_for(self, n_tokens):
        """Blocks needed to hold ``n_tokens`` cache rows."""
        return -(-max(int(n_tokens), 0) // self.block_size)

    def blocks_missing(self, have, n_tokens):
        """Blocks a sequence holding ``have`` blocks still needs to
        reach ``n_tokens`` cache rows — the incremental allocation unit
        of chunked prefill, where the table grows chunk by chunk
        instead of whole-prompt at admission."""
        return max(self.blocks_for(n_tokens) - int(have), 0)

    @property
    def free_count(self):
        return len(self._free)

    @property
    def used_count(self):
        return len(self._allocated)

    @property
    def occupancy(self):
        return len(self._allocated) / float(self.num_blocks)

    # -- alloc/free ----------------------------------------------------
    def alloc(self, n):
        """Take ``n`` blocks off the free list (all-or-nothing).  Under
        pressure, trie-only prefix blocks are evicted leaf-first before
        giving up — cached prefixes are an optimization, never a reason
        to fail an allocation."""
        n = int(n)
        if n < 0:
            raise MXNetError("alloc(%d): negative block count" % n)
        if n > len(self._free):
            self._evict_prefix_blocks(n - len(self._free))
        if n > len(self._free):
            raise CacheOOMError(
                "KV cache exhausted: need %d blocks, %d free of %d"
                % (n, len(self._free), self.num_blocks))
        out = [self._free.pop() for _ in range(n)]
        self._allocated.update(out)
        for b in out:
            self._ref[b] = 1
        self._update_gauges()
        return out

    def free(self, blocks):
        """Decref each block; a block returns to the free list only at
        refcount zero (shared blocks survive their first owner)."""
        for b in blocks:
            if b not in self._allocated:
                raise MXNetError(
                    "free(%r): block not allocated (double free would "
                    "alias two sequences onto one block)" % (b,))
            rc = self._ref.get(b, 0) - 1
            if rc < 0:
                raise MXNetError(
                    "free(%r): refcount went negative (double decref)"
                    % (b,))
            if rc == 0:
                self._allocated.discard(b)
                del self._ref[b]
                self._free.append(b)
            else:
                self._ref[b] = rc
        self._update_gauges()

    def incref(self, block):
        """Add one reference to an allocated block (a new sharer)."""
        if block not in self._allocated:
            raise MXNetError("incref(%r): block not allocated" % (block,))
        self._ref[block] += 1

    def ref(self, block):
        """Current refcount of a block (0 when not allocated)."""
        return self._ref.get(block, 0)

    def fork_for_write(self, block):
        """Copy-on-write fork: when ``block`` is shared (refcount > 1),
        allocate a private replacement, drop the caller's reference on
        the shared original, and return the new block id — the caller
        must copy the device rows and patch its table.  Returns ``None``
        when the block is exclusively owned (no fork needed).  With
        full-blocks-only sharing this never triggers on the engine's
        hot path (writes land at positions past every shared row); it
        exists as the safety valve the COW contract requires."""
        if self.ref(block) <= 1:
            return None
        new = self.alloc(1)[0]
        self.free([block])
        return new

    # -- prefix-sharing trie -------------------------------------------
    def _chain(self, tokens, n_blocks):
        """The trie keys for the first ``n_blocks`` full blocks of a
        token list: one tuple of ``block_size`` token ids per level."""
        bs = self.block_size
        return [tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
                for i in range(n_blocks)]

    def acquire_prefix(self, tokens):
        """Match the longest published block chain against ``tokens``
        and take one reference per matched block for the caller.
        Returns ``(blocks, n_rows)`` — the shared block ids (prefix of
        the caller's table) and the cache rows they cover.  At most
        ``(len(tokens) - 1) // block_size`` blocks are shared, so at
        least one prompt token always goes through chunked prefill and
        the chunk head still emits the sequence's first token."""
        if not self.prefix_sharing or not self._prefix_root:
            return [], 0
        max_share = (len(tokens) - 1) // self.block_size
        blocks = []
        node_children = self._prefix_root
        for key in self._chain(tokens, max_share):
            node = node_children.get(key)
            if node is None:
                break
            blocks.append(node["block"])
            node_children = node["children"]
        for b in blocks:
            self.incref(b)
        if blocks:
            self._prefix_hits += len(blocks)
            self._update_gauges()
        return blocks, len(blocks) * self.block_size

    def register_prefix(self, tokens, n_rows, blocks):
        """Publish a finished prefill's *full* blocks (rows
        ``[0, n_rows)``, table ``blocks``) into the trie.  Each newly
        published block gains one trie-held reference; chains already
        present keep their existing blocks (first writer wins — the
        content is identical by determinism of prefill)."""
        if not self.prefix_sharing:
            return 0
        full = int(n_rows) // self.block_size
        full = min(full, len(blocks))
        node_children = self._prefix_root
        published = 0
        for i, key in enumerate(self._chain(tokens, full)):
            node = node_children.get(key)
            if node is None:
                b = blocks[i]
                self.incref(b)
                node = {"block": b, "children": {}}
                node_children[key] = node
                self._prefix_blocks += 1
                published += 1
            node_children = node["children"]
        if published:
            self._update_gauges()
        return published

    def flush_prefixes(self):
        """Drop every trie reference (hot weight reload: cached rows
        were computed under the OLD weights, so serving them to new
        admissions would silently mix weight versions)."""
        dropped = []

        def _walk(children):
            for node in children.values():
                dropped.append(node["block"])
                _walk(node["children"])

        _walk(self._prefix_root)
        self._prefix_root = {}
        self._prefix_blocks = 0
        if dropped:
            self.free(dropped)
        return len(dropped)

    def _evict_prefix_blocks(self, need):
        """Free up to ``need`` blocks by evicting trie-ONLY blocks
        (refcount 1 — no live sequence references them) leaf-first, so
        every surviving chain stays a contiguous prefix."""
        freed = 0
        while freed < need:
            victim = None          # (children_dict, key) of a leaf

            def _find(children):
                nonlocal victim
                for key, node in children.items():
                    if victim is not None:
                        return
                    if not node["children"] and self.ref(
                            node["block"]) == 1:
                        victim = (children, key)
                    else:
                        _find(node["children"])

            _find(self._prefix_root)
            if victim is None:
                return freed
            children, key = victim
            block = children[key]["block"]
            del children[key]
            self._prefix_blocks -= 1
            self.free([block])
            PREFIX_EVICTIONS.inc()
            freed += 1
        return freed

    @property
    def prefix_stats(self):
        return {"trie_blocks": self._prefix_blocks,
                "hit_blocks": self._prefix_hits}

    def _update_gauges(self):
        used = free = total = hits = 0
        for cache in list(_LIVE):
            used += len(cache._allocated)
            free += len(cache._free)
            total += cache.num_blocks
            hits += cache._prefix_hits
        BLOCKS_USED.set(used)
        BLOCKS_FREE.set(free)
        CACHE_OCCUPANCY.set(used / float(total) if total else 0.0)
        PREFIX_HIT_BLOCKS.set(hits)

    # -- HBM census ----------------------------------------------------
    def attach_arrays(self, ndarrays):
        """Register the engine's cache NDArrays as the ``kv_cache``
        group of the HBM census (weakly — a collected engine stops
        contributing).  Bytes are per-ARRAY, so a block shared by many
        sequences is counted once by construction."""
        from ..telemetry import memory as _mem
        self._arrays = list(ndarrays)
        _refresh_bytes()
        _mem.track_group("kv_cache", _census_provider)
