"""ctypes bindings for the native runtime components (src/*.cc).

The shared library is built lazily with the in-tree Makefile on first
use (g++, no dependencies, <2s); every caller has a pure-Python
fallback, so a machine without a toolchain still works — the native
path exists because the reference's data runtime is C++
(3rdparty/dmlc-core recordio, src/io/). Measured against the Python
fallback on this image: offset scanning ~9x faster; record reads at
JPEG-typical sizes are memcpy-bound and equal, but the native reader
shares ONE read-only mmap across all of ImageRecordIter's decode
threads (no per-thread file handles, no GIL-held seek+read pairs).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "lib", "libmxtpu_io.so")
_SRC_DIR = os.path.join(_HERE, "..", "src")

_lock = threading.Lock()
_lib = None
_tried = False


def _stale():
    """True when the .so is missing or older than the native sources.
    A prebuilt .so without the src/ tree (installed package) is fresh."""
    if not os.path.exists(_LIB_PATH):
        return True
    if not os.path.isdir(_SRC_DIR):
        return False
    so_m = os.path.getmtime(_LIB_PATH)
    for fname in os.listdir(_SRC_DIR):
        if fname.endswith((".cc", ".h")) or fname == "Makefile":
            if os.path.getmtime(os.path.join(_SRC_DIR, fname)) > so_m:
                return True
    return False


def _build():
    """Build under an inter-process lock, compiling to a temp name and
    renaming atomically — concurrent dataloader processes must never
    dlopen a half-written .so."""
    import fcntl
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    lock_path = _LIB_PATH + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if not _stale():  # another process built it while we waited
                return
            tmp = "%s.tmp.%d" % (_LIB_PATH, os.getpid())
            subprocess.run(
                ["make", "-C", _SRC_DIR, "LIB=%s" % os.path.abspath(tmp)],
                check=True, capture_output=True, text=True)
            os.replace(tmp, _LIB_PATH)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def get_lib():
    """The loaded native library, or None (disable with
    MXTPU_NO_NATIVE=1)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("MXTPU_NO_NATIVE", "0") == "1":
            return None
        try:
            # rebuild when the .so is missing or older than the sources
            # (a stale binary silently resurrecting fixed bugs is worse
            # than a 2s build); an existing .so still loads if the
            # toolchain is gone.
            if _stale():
                try:
                    _build()
                except Exception:
                    if not os.path.exists(_LIB_PATH):
                        raise
            lib = ctypes.CDLL(_LIB_PATH)
        except Exception as e:
            logging.info("native io unavailable (%s); using the "
                         "pure-Python reader", e)
            return None
        lib.mxtpu_reader_open.restype = ctypes.c_void_p
        lib.mxtpu_reader_open.argtypes = [ctypes.c_char_p]
        lib.mxtpu_reader_close.argtypes = [ctypes.c_void_p]
        lib.mxtpu_reader_scan.restype = ctypes.c_int64
        lib.mxtpu_reader_scan.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
        lib.mxtpu_reader_read.restype = ctypes.c_int64
        lib.mxtpu_reader_read.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int32)]
        lib.mxtpu_free.argtypes = [ctypes.c_void_p]
        try:
            # absent when the library was built without libjpeg dev
            # files (the Makefile drops jpeg.cc); decode falls back to PIL
            lib.mxtpu_jpeg_dims.restype = ctypes.c_int
            lib.mxtpu_jpeg_dims.argtypes = [
                ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int)]
            lib.mxtpu_jpeg_decode.restype = ctypes.c_int
            lib.mxtpu_jpeg_decode.argtypes = [
                ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_long,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int)]
            lib._has_jpeg = True
        except AttributeError:
            lib._has_jpeg = False
        _lib = lib
        return _lib


_jpeg_scratch = threading.local()


def native_jpeg_decode(buf, gray=False):
    """Decode a JPEG byte buffer to an HWC uint8 numpy array with the
    native libjpeg path (GIL released for the whole decode), or None
    when the native library is unavailable or the data is not a JPEG
    this decoder handles (caller falls back to PIL).

    One native call per image: decodes into a per-thread scratch buffer
    (the decode op reports the needed dims via rc=-2 when the scratch is
    too small, so the header is parsed once per image, not twice)."""
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_jpeg", False):
        return None
    buf = bytes(buf)
    if len(buf) < 2 or buf[0] != 0xFF or buf[1] != 0xD8:
        return None  # not JPEG
    import numpy as np
    w = ctypes.c_int()
    h = ctypes.c_int()
    c = ctypes.c_int()
    scratch = getattr(_jpeg_scratch, "buf", None)
    if scratch is None:
        scratch = np.empty(1 << 20, np.uint8)
        _jpeg_scratch.buf = scratch
    rc = lib.mxtpu_jpeg_decode(
        buf, len(buf), int(gray), scratch.ctypes.data_as(ctypes.c_void_p),
        scratch.nbytes, ctypes.byref(w), ctypes.byref(h), ctypes.byref(c))
    if rc == -2:  # scratch too small; dims are filled — grow and retry
        scratch = np.empty(h.value * w.value * c.value, np.uint8)
        _jpeg_scratch.buf = scratch
        rc = lib.mxtpu_jpeg_decode(
            buf, len(buf), int(gray),
            scratch.ctypes.data_as(ctypes.c_void_p), scratch.nbytes,
            ctypes.byref(w), ctypes.byref(h), ctypes.byref(c))
    if rc != 0:
        return None
    n = h.value * w.value * c.value
    return scratch[:n].reshape(h.value, w.value, c.value).copy()


class NativeRecordReader:
    """mmap-backed RecordIO reader; thread-safe (stateless reads)."""

    def __init__(self, path):
        lib = get_lib()
        if lib is None:
            raise OSError("native io library unavailable")
        self._lib = lib
        self._handle = lib.mxtpu_reader_open(path.encode())
        if not self._handle:
            raise OSError("cannot open %s" % path)

    def scan_offsets(self):
        ptr = ctypes.POINTER(ctypes.c_int64)()
        n = self._lib.mxtpu_reader_scan(self._handle, ctypes.byref(ptr))
        if n < 0:
            raise IOError("invalid RecordIO magic (or out of memory) "
                          "during native scan")
        try:
            import numpy as _np
            return _np.ctypeslib.as_array(ptr, shape=(n,)).tolist() \
                if n else []
        finally:
            self._lib.mxtpu_free(ptr)

    def read_at(self, offset):
        """Record payload at a byte offset, as bytes."""
        data = ctypes.POINTER(ctypes.c_uint8)()
        needs_free = ctypes.c_int32(0)
        n = self._lib.mxtpu_reader_read(self._handle, offset,
                                        ctypes.byref(data),
                                        ctypes.byref(needs_free))
        if n < 0:
            raise IOError("corrupt record at offset %d" % offset)
        try:
            return ctypes.string_at(data, n)
        finally:
            if needs_free.value:
                self._lib.mxtpu_free(data)

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.mxtpu_reader_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
