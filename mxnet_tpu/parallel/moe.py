"""Mixture-of-Experts with expert parallelism over an ``ep`` mesh axis.

The reference (MXNet ~1.2) predates MoE entirely (SURVEY.md §2.3 lists
expert parallelism among the absent modern strategies), so — like ring
attention and the GPipe pipeline — this is a new TPU-native capability:
Switch/top-k routing in the Mesh-TensorFlow einsum formulation (static
shapes, no data-dependent gather loops — exactly what XLA wants), with
the expert-stacked parameters and the dispatched token blocks sharded
over ``ep`` via ``with_sharding_constraint`` so GSPMD inserts the
all-to-alls that move token blocks to their experts over ICI.

* ``switch_moe``      — routed expert-FFN layer: returns (y, aux_loss)
  where aux_loss is the standard load-balancing loss (Switch
  Transformer eq. 4: E * Σ_e f_e · P_e).
* ``moe_reference``   — dense oracle: every token through every
  expert, mixed by the FULL softmax over all experts. It equals
  switch_moe only when ``k == n_experts`` and no token overflows
  capacity (the tests pin exactly that case, plus a separate top-1
  oracle); for ``k < n_experts`` switch_moe combines with the
  un-renormalized top-k probabilities, so the two differ even with
  infinite capacity.

Capacity semantics: each expert processes at most
``ceil(k·N/E · capacity_factor)`` tokens; overflowing tokens are
dropped from that expert (their combine weight is zero), the standard
Switch behavior.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["switch_moe", "moe_reference", "init_moe_params"]


def init_moe_params(key, d_model, d_hidden, n_experts, dtype=jnp.float32):
    """Router + expert-stacked FFN parameters (leading axis E — the one
    that shards over ``ep``)."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / math.sqrt(d_model)
    s2 = 1.0 / math.sqrt(d_hidden)
    return {
        "router": (jax.random.normal(k1, (d_model, n_experts),
                                     jnp.float32) * s1).astype(dtype),
        "w1": (jax.random.normal(k2, (n_experts, d_model, d_hidden),
                                 jnp.float32) * s1).astype(dtype),
        "b1": jnp.zeros((n_experts, d_hidden), dtype),
        "w2": (jax.random.normal(k3, (n_experts, d_hidden, d_model),
                                 jnp.float32) * s2).astype(dtype),
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def _expert_ffn(params, xe):
    """xe: (E, C, d) — each expert's token block through its own FFN."""
    h = jnp.einsum("ecd,edh->ech", xe, params["w1"]) \
        + params["b1"][:, None, :]
    h = jax.nn.relu(h)
    return jnp.einsum("ech,ehd->ecd", h, params["w2"]) \
        + params["b2"][:, None, :]


def moe_reference(params, x):
    """Dense oracle: every token through every expert, weighted by the
    full softmax gate — the no-capacity-limit ideal."""
    probs = jax.nn.softmax(x @ params["router"], axis=-1)      # (N, E)
    h = jnp.einsum("nd,edh->neh", x, params["w1"]) \
        + params["b1"][None]
    h = jax.nn.relu(h)
    y = jnp.einsum("neh,ehd->ned", h, params["w2"]) \
        + params["b2"][None]
    return jnp.einsum("ne,ned->nd", probs, y)


def switch_moe(params, x, k=1, capacity_factor=1.25, mesh=None,
               axis="ep"):
    """Top-k routed MoE layer. x: (N, d_model) tokens (flatten (B, T)
    outside). Returns (y, aux_loss).

    With ``mesh``, the expert-stacked tensors are sharding-constrained
    to P(axis) on their leading E dim — under jit over that mesh, GSPMD
    partitions the expert FFNs across ``ep`` and inserts the
    all-to-alls for the dispatch/combine einsums.
    """
    N, d = x.shape
    E = params["router"].shape[1]
    k = int(k)
    C = max(1, int(math.ceil(k * N / E * float(capacity_factor))))

    def constrain(v):
        """Pin the leading (expert) axis to the ep mesh axis."""
        if mesh is None:
            return v
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P(axis, *([None] * (v.ndim - 1)))
        return lax.with_sharding_constraint(
            v, NamedSharding(mesh, spec))

    logits = x @ params["router"]                               # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, k)                   # (N, k)

    # position of each (token, choice) in its expert's queue: running
    # count of earlier assignments to the same expert (einsum-style
    # cumsum dispatch — static shapes, no sorting)
    assign = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)       # (N,k,E)
    flat = assign.reshape(N * k, E)
    pos_in_expert = jnp.cumsum(flat, axis=0) - flat             # (N*k, E)
    pos = (pos_in_expert * flat).sum(-1).reshape(N, k)          # (N, k)
    keep = pos < C
    gate_vals = gate_vals * keep                                # drop overflow

    # dispatch (N, k, E, C) one-hots contracted on the fly
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, C), C,
                            dtype=x.dtype)                      # (N,k,C)
    disp = jnp.einsum("nke,nkc->nec", assign.astype(x.dtype),
                      pos_oh * keep[..., None])                 # (N,E,C)
    xe = jnp.einsum("nec,nd->ecd", disp, x)                     # (E,C,d)
    xe = constrain(xe)

    # expert-parallel FFN: the expert-stacked params (by NAME — a shape
    # test would misfire when d_model == n_experts) shard over ep
    eparams = {kk: (constrain(v) if kk in ("w1", "b1", "w2", "b2")
                    else v)
               for kk, v in params.items()}
    ye = _expert_ffn(eparams, xe)                               # (E,C,d)
    ye = constrain(ye)

    # combine: weight each fetched expert output by its gate
    combine = jnp.einsum("nec,nke,nk->nec", disp,
                         assign.astype(x.dtype), gate_vals)     # (N,E,C)
    y = jnp.einsum("nec,ecd->nd", combine, ye)

    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * P_e
    f = (assign[:, 0].astype(jnp.float32)).mean(0)              # (E,)
    p = probs.astype(jnp.float32).mean(0)
    aux = E * jnp.sum(f * p)
    return y, aux
