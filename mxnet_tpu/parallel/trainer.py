"""Fused data/tensor-parallel training step.

The reference's training step is Python-orchestrated: per-device executors
run fwd/bwd (executor_group.py:436,571), kvstore push/pull aggregates
gradients (model.py:145), then per-key fused optimizer ops update weights.
The TPU-native realization collapses all of that into ONE pjit'd XLA
computation per step: forward + backward + cross-device gradient reduction
(inserted by GSPMD from the shardings) + optimizer update, with parameter /
state buffers donated so HBM holds a single copy.

Sharding model (SURVEY.md §2.3):
* batch axis       → mesh axis ``dp``  (replaces kvstore local/device/nccl)
* weight shards    → mesh axis ``tp``  (GSPMD tensor parallelism; the
                     reference's closest analog is group2ctx model
                     parallelism, graph_executor.cc:408)
* gradients        → psum over ``dp`` inserted by XLA, riding ICI

This is the component bench.py and the Module's `fused` mode drive.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..ops import optimizer_ops as _oo
from .. import telemetry as _telemetry

__all__ = ["TrainStep", "default_tp_rule"]

# trace-time retrace witness + program-registry registration, same
# RetraceSite contract as executor/fused_fit/kvstore (the step body
# calls _note_retrace(); step() dispatches through _SITE.timed)
PARALLEL_RETRACES = _telemetry.REGISTRY.counter(
    "parallel_step_retraces",
    "parallel TrainStep program (re)traces (trace-time witness)",
    vital=True)
_SITE = _telemetry.RetraceSite(PARALLEL_RETRACES,
                               _telemetry.JIT_COMPILE_MS,
                               site="parallel_step")
_note_retrace = _SITE.note


def default_tp_rule(name, shape, mesh):
    """Heuristic parameter PartitionSpec for a mesh with a 'tp' axis:
    shard the output-channel axis of large matmul/conv weights, replicate
    everything else. GSPMD propagates the rest of the sharding."""
    if "tp" not in mesh.axis_names:
        return P()
    tp = mesh.shape["tp"]
    if len(shape) >= 2 and shape[0] % tp == 0 and shape[0] >= 2 * tp:
        return P("tp")
    return P()


def _wd_for(optimizer, name):
    """Per-parameter weight decay keyed by NAME (not index — this TrainStep
    may not share idx2name with a Module that used the same optimizer).
    Reproduces Optimizer.set_wd_mult's default: wd=0 unless the name ends in
    _weight/_gamma (reference optimizer.py:330)."""
    if name in optimizer.param_dict:
        return optimizer.wd * optimizer.param_dict[name].wd_mult
    if name in optimizer.wd_mult:
        return optimizer.wd * optimizer.wd_mult[name]
    if not (name.endswith("_weight") or name.endswith("_gamma")):
        return 0.0
    return optimizer.wd


def _functional_update(optimizer, idx, name, weight, grad, state, lr):
    """Apply ``optimizer`` to one parameter functionally, using the same
    pure update ops the eager path uses (ops/optimizer_ops.py; reference
    src/operator/optimizer_op.cc). Returns (new_weight, new_state)."""
    from .. import optimizer as _opt

    wd = _wd_for(optimizer, name)
    lr = lr * (optimizer.lr_mult.get(name, 1.0)
               if name not in optimizer.param_dict else
               optimizer.param_dict[name].lr_mult)
    kw = dict(rescale_grad=optimizer.rescale_grad,
              clip_gradient=(optimizer.clip_gradient
                             if optimizer.clip_gradient is not None else -1.0))

    if isinstance(optimizer, _opt.SGD):
        mom = optimizer.momentum
        use_mp = optimizer.multi_precision and weight.dtype in (
            jnp.float16, jnp.bfloat16)
        if use_mp:
            if mom:
                m, w32 = state
                new_w, new_m, new_w32 = _oo.mp_sgd_mom_update(
                    weight, grad, m, w32, lr=lr, momentum=mom, wd=wd, **kw)
                return new_w, (new_m, new_w32)
            (w32,) = state
            new_w, new_w32 = _oo.mp_sgd_update(weight, grad, w32, lr=lr,
                                               wd=wd, **kw)
            return new_w, (new_w32,)
        if mom:
            (m,) = state
            new_w, new_m = _oo.sgd_mom_update(weight, grad, m, lr=lr,
                                              momentum=mom, wd=wd, **kw)
            return new_w, (new_m,)
        return _oo.sgd_update(weight, grad, lr=lr, wd=wd, **kw), ()
    if isinstance(optimizer, _opt.Signum):
        (m,) = state
        new_w, new_m = _oo.signum_update(
            weight, grad, m, lr=lr, momentum=optimizer.momentum, wd=wd,
            wd_lh=getattr(optimizer, "wd_lh", 0.0), **kw)
        return new_w, (new_m,)
    if isinstance(optimizer, _opt.Adam):
        mean, var = state
        new_w, new_mean, new_var = _oo.adam_update(
            weight, grad, mean, var, lr=lr, beta1=optimizer.beta1,
            beta2=optimizer.beta2, epsilon=optimizer.epsilon, wd=wd, **kw)
        return new_w, (new_mean, new_var)
    if isinstance(optimizer, _opt.RMSProp):
        if optimizer.clip_weights:
            kw["clip_weights"] = optimizer.clip_weights
        if optimizer.centered:
            n, g, delta = state
            new_w, new_n, new_g, new_d = _oo.rmspropalex_update(
                weight, grad, n, g, delta, lr=lr, gamma1=optimizer.gamma1,
                gamma2=optimizer.gamma2, epsilon=optimizer.epsilon, wd=wd,
                **kw)
            return new_w, (new_n, new_g, new_d)
        (n,) = state
        new_w, new_n = _oo.rmsprop_update(
            weight, grad, n, lr=lr, gamma1=optimizer.gamma1,
            epsilon=optimizer.epsilon, wd=wd, **kw)
        return new_w, (new_n,)
    if isinstance(optimizer, _opt.AdaGrad):
        (h,) = state
        new_w, new_h = _oo.adagrad_update(weight, grad, h, lr=lr,
                                          epsilon=optimizer.eps, wd=wd, **kw)
        return new_w, (new_h,)
    raise MXNetError(
        "fused TrainStep supports sgd/signum/adam/rmsprop/adagrad; %r must "
        "run through Module.update()" % type(optimizer).__name__)


def _init_state(optimizer, weight):
    """fp32 state pytree per parameter (mirrors Optimizer.create_state)."""
    from .. import optimizer as _opt
    w32 = lambda: jnp.asarray(weight, jnp.float32)
    zeros = lambda: jnp.zeros(weight.shape, jnp.float32)
    if isinstance(optimizer, _opt.SGD):
        use_mp = optimizer.multi_precision and weight.dtype in (
            jnp.float16, jnp.bfloat16)
        if use_mp:
            return (zeros(), w32()) if optimizer.momentum else (w32(),)
        return (zeros(),) if optimizer.momentum else ()
    if isinstance(optimizer, _opt.Signum):
        return (zeros(),)
    if isinstance(optimizer, _opt.Adam):
        return (zeros(), zeros())
    if isinstance(optimizer, _opt.RMSProp):
        return (zeros(), zeros(), zeros()) if optimizer.centered else (zeros(),)
    if isinstance(optimizer, _opt.AdaGrad):
        return (zeros(),)
    return ()


def _device_weight_rule(initializer, shape, dtype):
    """fn(key) -> device array applying ``initializer``'s WEIGHT rule
    (Xavier/Normal/Uniform/Zero/One/Constant), or None."""
    from .. import initializer as _init

    cls = type(initializer)
    if isinstance(initializer, _init.Zero):
        return lambda key: jnp.zeros(shape, dtype)
    if isinstance(initializer, _init.One):
        return lambda key: jnp.ones(shape, dtype)
    if isinstance(initializer, _init.Constant):
        return lambda key: jnp.full(shape, initializer.value, dtype)
    if isinstance(initializer, _init.Xavier) \
            and cls._init_weight is _init.Xavier._init_weight:
        if len(shape) < 2:
            return None
        hw = 1.0
        for s in shape[2:]:
            hw *= s
        fan_in, fan_out = shape[1] * hw, shape[0] * hw
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[initializer.factor_type]
        scale = float(_np.sqrt(initializer.magnitude / factor))
        if initializer.rnd_type == "uniform":
            return lambda key: jax.random.uniform(
                key, shape, jnp.float32, -scale, scale).astype(dtype)
        return lambda key: (jax.random.normal(key, shape, jnp.float32)
                            * scale).astype(dtype)
    if cls is _init.Normal:
        s = float(initializer.sigma)
        return lambda key: (jax.random.normal(key, shape, jnp.float32)
                            * s).astype(dtype)
    if cls is _init.Uniform:
        s = float(initializer.scale)
        return lambda key: jax.random.uniform(
            key, shape, jnp.float32, -s, s).astype(dtype)
    return None


def _device_init_rule(initializer, name, attrs, shape, dtype):
    """Device-side analog of Initializer.__call__'s name dispatch
    (initializer.py:55): returns fn(key) -> jax array, or None when the
    (initializer, name) pair has no closed-form device rule
    (Orthogonal/Bilinear/..., packed RNN vecs, custom subclasses).

    TPU-first: the reference initializes on the host and copies every
    parameter to the device; generating with XLA's on-chip RNG instead
    means a multi-GB model materializes in HBM without a single
    host->device weight transfer."""
    import json as _json

    from .. import initializer as _init

    if attrs and attrs.get("__init__"):
        # per-variable init attr (Variable(init=...)): the host path
        # applies that initializer's WEIGHT rule — mirror it on device
        # (bailing here would force e.g. multi-GB MoE expert stacks
        # through host RAM)
        try:
            klass, kw = _json.loads(attrs["__init__"])
            inst = _init.get(klass, **kw)
        except Exception:
            return None
        return _device_weight_rule(inst, shape, dtype)
    cls = type(initializer)
    # any overridden dispatch or rule method means the initializer has
    # custom semantics (Mixed, Load, user subclasses) — host path only
    if cls.__call__ is not _init.Initializer.__call__:
        return None
    base = _init.Initializer
    for meth in ("_init_bias", "_init_gamma", "_init_beta", "_init_zero",
                 "_init_one", "_init_default"):
        if getattr(cls, meth) is not getattr(base, meth):
            return None
    lname = name.lower()
    if lname.endswith(("_bias", "_beta", "_moving_mean", "_running_mean",
                       "_moving_avg", "_min", "_max")):
        return lambda key: jnp.zeros(shape, dtype)
    if lname.endswith(("_gamma", "_moving_var", "_running_var")):
        return lambda key: jnp.ones(shape, dtype)
    if lname.endswith("_parameters"):
        return None
    return _device_weight_rule(initializer, shape, dtype)


class TrainStep:
    """symbol + optimizer + mesh → one compiled training step.

    Usage (see bench.py)::

        mesh = Mesh(np.array(jax.devices()).reshape(-1), ('dp',))
        ts = TrainStep(sym, optimizer, mesh=mesh,
                       data_shapes={'data': (256, 3, 224, 224)},
                       label_shapes={'softmax_label': (256,)})
        ts.init_params(mx.init.Xavier())
        for batch in loader:
            outs = ts.step(batch)          # donates & replaces params
    """

    def __init__(self, symbol, optimizer, data_shapes, label_shapes=None,
                 mesh=None, dtype="float32", tp_rule=default_tp_rule,
                 batch_axis="dp"):
        from ..executor import _build_graph_fn

        self._symbol = symbol
        self._optimizer = optimizer
        self._graph_fn = _build_graph_fn(symbol)
        self._mesh = mesh
        self._batch_axis = batch_axis
        self._tp_rule = tp_rule

        input_shapes = dict(data_shapes)
        input_shapes.update(label_shapes or {})
        self._input_names = list(input_shapes)
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names if n not in input_shapes]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        type_kwargs = {n: dtype for n in data_shapes} if dtype != "float32" else {}
        arg_shapes, arg_types, aux_shapes, aux_types = \
            symbol.infer_shape_type(input_shapes, type_kwargs)
        self._arg_shapes = dict(zip(arg_names, arg_shapes))
        self._arg_types = dict(zip(arg_names, arg_types))
        self._aux_shapes = dict(zip(self._aux_names, aux_shapes))
        self._aux_types = dict(zip(self._aux_names, aux_types))

        # wd/lr multipliers are resolved by NAME inside _functional_update
        # (_wd_for), so the optimizer's idx2name — possibly owned by a
        # Module with different indices — is never touched
        self._idx = {n: i for i, n in enumerate(self._param_names)}

        self.params = None       # name -> jax.Array
        self.states = None       # name -> tuple of jax.Array
        self.auxs = None         # name -> jax.Array
        self._step_fn = None
        self._nstep = 0
        from .. import random as _rand
        self._base_seed = int(_rand.next_seed())

    # ------------------------------------------------------------------
    def _param_sharding(self, name):
        if self._mesh is None:
            return None
        spec = (self._tp_rule(name, self._arg_shapes[name], self._mesh)
                if self._tp_rule else P())
        return NamedSharding(self._mesh, spec)

    def _batch_sharding(self):
        if self._mesh is None:
            return None
        return NamedSharding(self._mesh, P(self._batch_axis))

    def _repl_sharding(self):
        if self._mesh is None:
            return None
        return NamedSharding(self._mesh, P())

    def init_params(self, initializer, arg_params=None, aux_params=None,
                    device_init=True):
        """Initialize parameters. With ``device_init`` (default), params
        whose initializer rule has a closed form (Xavier/Normal/Uniform/
        Zero/One/Constant + the standard name-suffix rules) generate
        directly on the accelerator with XLA's RNG — no host->device
        weight transfer at all (the reference always inits on cpu and
        copies, module.py:270; for multi-GB models over PCIe/tunnel the
        device path is the difference between seconds and minutes).
        Everything else falls back to the host initializer."""
        from ..initializer import InitDesc
        from ..ndarray.ndarray import NDArray

        attrs = self._symbol.attr_dict()
        key = jax.random.key(self._base_seed)

        def materialize(name, shp, dt, provided, sharding):
            nonlocal key
            if provided is not None:
                host = provided.asnumpy() \
                    if isinstance(provided, NDArray) else provided
                return jax.device_put(jnp.asarray(host, dt), sharding)
            if device_init:
                rule = _device_init_rule(initializer, name,
                                         attrs.get(name), shp, dt)
                if rule is not None:
                    key, sub = jax.random.split(key)
                    return jax.device_put(rule(sub), sharding)
            nd_host = NDArray(jnp.zeros(shp, dt))
            initializer(InitDesc(name, attrs.get(name)), nd_host)
            return jax.device_put(jnp.asarray(nd_host.asnumpy(), dt),
                                  sharding)

        params = {}
        for name in self._param_names:
            params[name] = materialize(
                name, self._arg_shapes[name], self._arg_types[name],
                (arg_params or {}).get(name), self._param_sharding(name))
        auxs = {}
        for name in self._aux_names:
            auxs[name] = materialize(
                name, self._aux_shapes[name], self._aux_types[name],
                (aux_params or {}).get(name), self._repl_sharding())
        states = {n: tuple(
            jax.device_put(s, self._param_sharding(n))
            for s in _init_state(self._optimizer, params[n]))
            for n in self._param_names}
        self.params, self.states, self.auxs = params, states, auxs

    # ------------------------------------------------------------------
    def _build_step(self):
        graph_fn = self._graph_fn
        optimizer = self._optimizer
        param_names = self._param_names
        idx = self._idx

        def step_fn(params, states, auxs, batch, lr, seed):
            _note_retrace()   # trace-time host side effect only

            def f(p):
                outs, new_auxs = graph_fn({**batch, **p}, auxs, seed, True)
                return outs, new_auxs

            outs, vjp_fn, new_auxs = jax.vjp(f, params, has_aux=True)
            cts = [jnp.ones_like(o) for o in outs]
            (grads,) = vjp_fn(cts)
            new_params, new_states = {}, {}
            for n in param_names:
                g = grads[n]
                if g is None:
                    new_params[n], new_states[n] = params[n], states[n]
                    continue
                new_params[n], new_states[n] = _functional_update(
                    optimizer, idx[n], n, params[n], g, states[n], lr)
            return new_params, new_states, new_auxs, outs

        from ..aot.store import safe_donate_argnums as _donate
        if self._mesh is None:
            return jax.jit(step_fn, donate_argnums=_donate((0, 1, 2)))

        param_sh = {n: self._param_sharding(n) for n in param_names}
        state_sh = {n: tuple(param_sh[n] for _ in self.states[n])
                    for n in param_names}
        aux_sh = {n: self._repl_sharding() for n in self._aux_names}
        batch_sh = {n: self._batch_sharding() for n in self._input_names}
        repl = self._repl_sharding()
        return jax.jit(
            step_fn,
            in_shardings=(param_sh, state_sh, aux_sh, batch_sh, repl, repl),
            out_shardings=(param_sh, state_sh, aux_sh, None),
            donate_argnums=_donate((0, 1, 2)))

    def step(self, batch):
        """Run one training step; ``batch`` maps input name → array.
        Returns the forward outputs."""
        if self.params is None:
            raise MXNetError("call init_params() first")
        if self._step_fn is None:
            self._step_fn = self._build_step()
        self._nstep += 1
        optimizer = self._optimizer
        optimizer.num_update = max(optimizer.num_update, self._nstep)
        lr = (optimizer.lr_scheduler(optimizer.num_update)
              if optimizer.lr_scheduler is not None else optimizer.lr)
        from .. import optimizer as _opt
        if isinstance(optimizer, _opt.Adam):
            # Adam bias correction folded into lr host-side, one global t:
            # in the fused whole-graph step EVERY parameter updates EVERY
            # step, so the single counter equals the reference's per-index
            # update counts exactly (indexes can only diverge in the eager
            # per-key path, where optimizer.py keeps per-index counts).
            t = self._nstep
            lr *= ((1.0 - optimizer.beta2 ** t) ** 0.5
                   / (1.0 - optimizer.beta1 ** t))
        # cast to the inferred input dtype (e.g. TrainStep(dtype='bfloat16')
        # on a symbol with no explicit Cast) before placing on device
        def _place(n, v):
            dt = self._arg_types.get(n)
            # fast path only for UNcommitted arrays (already free to live
            # on the default device); a cpu-committed iterator batch must
            # be re-placed or the jit sees mixed devices
            if isinstance(v, jax.Array) and (dt is None or v.dtype == dt) \
                    and self._mesh is None and not getattr(v, "committed",
                                                           True):
                return v
            v = jnp.asarray(v, dt)
            if self._mesh is not None:
                return jax.device_put(v, self._batch_sharding())
            if getattr(v, "committed", False):
                # cpu-context iterator batch: move to the step's device
                v = jax.device_put(v, jax.devices()[0])
            return v

        batch = {n: _place(n, v) for n, v in batch.items()}
        seed = _np.uint32((self._base_seed + self._nstep * 2654435761)
                          & 0x7FFFFFFF)
        self.params, self.states, self.auxs, outs = _SITE.timed(
            self._step_fn, self.params, self.states, self.auxs, batch,
            jnp.float32(lr), seed)
        return outs

    # ------------------------------------------------------------------
    def get_params(self):
        """Gather params/auxs to host NDArrays (for checkpointing)."""
        from ..ndarray.ndarray import NDArray
        arg = {n: NDArray(jnp.asarray(v)) for n, v in self.params.items()}
        aux = {n: NDArray(jnp.asarray(v)) for n, v in self.auxs.items()}
        return arg, aux
