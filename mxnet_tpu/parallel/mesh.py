"""Device-mesh helpers.

The reference discovers GPU link topology to build reduction trees
(src/kvstore/gpu_topology.h, comm_tree.h). On TPU the interconnect is the
ICI torus and XLA schedules collectives over it, so "topology" reduces to
choosing mesh axes: ``dp`` (data), ``tp`` (tensor/model), ``pp``
(pipeline), ``sp`` (sequence/context), ``ep`` (expert).
"""
from __future__ import annotations

import numpy as _np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_parallel_mesh", "batch_sharding",
           "replicated_sharding", "shard_batch", "current_mesh"]

_CURRENT = {"mesh": None}


def make_mesh(axis_sizes: dict, devices=None) -> Mesh:
    """Create a Mesh with named axes, e.g. make_mesh({'dp': 4, 'tp': 2})."""
    devices = devices if devices is not None else jax.devices()
    names = tuple(axis_sizes)
    sizes = tuple(int(axis_sizes[n]) for n in names)
    total = int(_np.prod(sizes))
    if total > len(devices):
        raise ValueError("mesh needs %d devices, only %d visible"
                         % (total, len(devices)))
    arr = _np.array(devices[:total]).reshape(sizes)
    mesh = Mesh(arr, names)
    _CURRENT["mesh"] = mesh
    return mesh


def data_parallel_mesh(contexts) -> Mesh:
    """Mesh with a single 'dp' axis over the given Contexts."""
    devs = [c.jax_device for c in contexts]
    mesh = Mesh(_np.array(devs), ("dp",))
    _CURRENT["mesh"] = mesh
    return mesh


def current_mesh():
    return _CURRENT["mesh"]


def batch_sharding(mesh, axis="dp"):
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh):
    return NamedSharding(mesh, P())


def shard_batch(x, mesh, axis="dp"):
    """Place a host batch sharded along its leading dim over the mesh."""
    return jax.device_put(x, batch_sharding(mesh, axis))
