"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference (MXNet ~1.2) predates attention entirely (SURVEY.md §5.7),
but long-context scaling is first-class in this framework: sequences too
long for one chip's HBM shard across a ``sp`` mesh axis, and attention
runs as either

* **ring attention** (`ring_attention`) — K/V blocks rotate around the
  ring via ``lax.ppermute`` while each device keeps a flash-attention-
  style online softmax (running max + denominator) over its local Q
  shard. Compute overlaps the ICI transfer of the next block; memory per
  chip is O(T/n) with no full-sequence materialization anywhere.
* **Ulysses all-to-all** (`ulysses_attention`) — ``lax.all_to_all``
  re-shards from sequence-split to head-split, runs dense attention on
  full sequences per head group, and re-shards back. Cheaper collective
  volume for moderate T; requires heads % sp == 0.

Both are pure jax (shard_map + collectives), differentiate through the
collectives, and validate on a virtual CPU mesh exactly like the rest of
the multi-chip suite; `attention_reference` is the single-device oracle.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["attention_reference", "ring_attention", "ulysses_attention"]

_NEG_INF = -1e30

def _shard_map():
    """shard_map with the replication checker OFF.  The causal ring
    skips fully-masked blocks with a ``lax.cond`` whose predicate
    (``src <= rank``) is device-varying; both branches produce values
    varying over the same mesh axes, but the static rep/vma checker
    cannot type a varying-predicate cond and rejects the (correct)
    program — jax's own error message prescribes ``check_rep=False``
    as the workaround.  Gradient parity against the single-device
    oracle is pinned by tests/test_ring_attention.py."""
    import functools
    import inspect
    try:
        sm = jax.shard_map              # jax >= 0.8
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm
    try:
        params = inspect.signature(sm).parameters
    except (TypeError, ValueError):
        params = ()
    for kw in ("check_rep", "check_vma"):   # renamed across versions
        if kw in params:
            return functools.partial(sm, **{kw: False})
    return sm


def attention_reference(q, k, v, causal=False, scale=None):
    """Plain softmax attention, (B, T, H, D) layout — the single-device
    oracle the parallel forms must match."""
    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block_attn(q, k, v, q_off, k_off, causal, scale, o, l, m):
    """One online-softmax accumulation step over a K/V block.
    q: (B, Tq, H, D); k/v: (B, Tk, H, D); o/l/m running stats."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        qpos = q_off + jnp.arange(tq)
        kpos = k_off + jnp.arange(tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows (no valid key yet): keep them at zero
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = (o * alpha[..., None]
             + jnp.einsum("bhqk,bkhd->bhqd", p, v))
    return o_new, l_new, m_new


def ring_attention(q, k, v, mesh, axis="sp", causal=False, scale=None,
                   batch_axis=None):
    """Attention over sequences sharded on ``axis`` (see module doc).
    q/k/v: (B, T, H, D) global arrays (or shardable values); returns the
    (B, T, H, D) attention output with the same sharding. Pass
    ``batch_axis`` to compose with data parallelism (batch sharded over
    that mesh axis)."""
    from jax.sharding import PartitionSpec as P

    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    n = mesh.shape[axis]

    spec = P(batch_axis, axis, None, None)

    def local(ql, kl, vl):
        # ql/kl/vl: (B, T/n, H, D) local shards
        rank = lax.axis_index(axis)
        tq = ql.shape[1]
        b, h = ql.shape[0], ql.shape[2]
        o0 = jnp.zeros((b, h, tq, d), jnp.float32)
        l0 = jnp.zeros((b, h, tq), jnp.float32)
        m0 = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
        # constants start device-invariant; mark them varying over every
        # sharded axis so the scan carry types line up (shard_map vma)
        vary_axes = tuple(a for a in (batch_axis, axis) if a)
        if hasattr(lax, "pcast"):
            o0, l0, m0 = (lax.pcast(x, vary_axes, to="varying")
                          for x in (o0, l0, m0))
        perm = [(j, (j - 1) % n) for j in range(n)]

        # block 0 is local — no rotation; iterations 1..n-1 rotate THEN
        # compute, so exactly n-1 ppermutes happen per call (XLA overlaps
        # each transfer with the preceding block's compute on real ICI)
        k0 = kl.astype(jnp.float32)
        v0 = vl.astype(jnp.float32)
        o0, l0, m0 = _block_attn(ql, k0, v0, rank * tq, rank * tq,
                                 causal, scale, o0, l0, m0)

        def step(carry, i):
            o, l, m, k_cur, v_cur = carry
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
            src = (rank + i) % n            # block origin of k_cur

            def compute(olm):
                return _block_attn(ql, k_cur, v_cur, rank * tq, src * tq,
                                   causal, scale, *olm)

            if causal:
                # blocks strictly above the causal diagonal (src > rank)
                # are fully masked — skip their QK^T/PV entirely. (Load
                # stays imbalanced across the ring — the zigzag block
                # assignment that fixes it is a layout choice above this
                # kernel.)
                o, l, m = lax.cond(src <= rank, compute,
                                   lambda olm: olm, (o, l, m))
            else:
                o, l, m = compute((o, l, m))
            return (o, l, m, k_cur, v_cur), None

        if n > 1:
            (o, l, m, _, _), _ = lax.scan(
                step, (o0, l0, m0, k0, v0), jnp.arange(1, n))
        else:
            o, l, m = o0, l0, m0
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return jnp.transpose(out, (0, 2, 1, 3)).astype(ql.dtype)

    fn = _shard_map()(local, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)
    return fn(q, k, v)


def ulysses_attention(q, k, v, mesh, axis="sp", causal=False, scale=None,
                      batch_axis=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses form): re-shard
    seq-split -> head-split, dense attention per head group, re-shard
    back. Requires num_heads %% mesh.shape[axis] == 0."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    h = q.shape[2]
    if h % n != 0:
        raise ValueError("ulysses_attention: %d heads not divisible by "
                         "sp=%d" % (h, n))
    spec = P(batch_axis, axis, None, None)

    def local(ql, kl, vl):
        # (B, T/n, H, D) -> (B, T, H/n, D)
        def fwd(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        def bwd(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        out = attention_reference(fwd(ql), fwd(kl), fwd(vl),
                                  causal=causal, scale=scale)
        return bwd(out)

    fn = _shard_map()(local, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)
    return fn(q, k, v)
