"""Pipeline parallelism: GPipe-style microbatched stage execution over a
``pp`` mesh axis.

The reference has NO pipeline parallelism (SURVEY.md §2.3 marks it
ABSENT — its engine merely overlaps independent graph branches), so
this is a new TPU-native capability beside ring attention: the model's
layers split into S stages, each stage's parameters live on one slice
of the ``pp`` mesh axis, and microbatches stream through the stages
with ``jax.lax.ppermute`` moving activations stage-to-stage over ICI.

Schedule: the classic GPipe loop — with S stages and M microbatches,
one jitted step runs S+M-1 ticks; on each tick every stage computes its
current microbatch (device-parallel across the ``pp`` axis) and the
activations rotate one hop. Bubble fraction = (S-1)/(S+M-1), amortized
by choosing M >> S. Backward rides jax.grad straight through the
``ppermute``s (its transpose is the reverse rotation), so one
``value_and_grad`` of the scheduled forward IS pipelined backward —
no hand-written 1F1B needed for correctness.

All stages must share one layer signature (the classic homogeneous-
stack assumption); embed/head layers live outside the pipelined trunk.

Works like the rest of the parallel package: pure jax + shard_map,
validated on a virtual CPU mesh (tests/test_pipeline.py), composes
with a ``dp`` axis for data parallelism.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pipeline_apply", "stack_stage_params"]


def _shard_map():
    try:
        return jax.shard_map          # jax >= 0.8
    except AttributeError:
        from jax.experimental.shard_map import shard_map
        return shard_map


def stack_stage_params(stage_params):
    """Stack a list of S per-stage parameter pytrees into one pytree
    whose leaves carry a leading stage axis (to shard over ``pp``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def pipeline_apply(stage_fn, stacked_params, x, mesh, n_microbatches,
                   axis="pp", batch_axis=None):
    """Run ``x`` through S pipeline stages.

    stage_fn(params, x) -> y   — one stage's computation; every stage
        uses the same signature/shapes (homogeneous stack).
    stacked_params — pytree with leading stage axis S == mesh.shape[axis]
        (see stack_stage_params); sharded so stage i's slice lives on
        pp-coordinate i.
    x — (B, ...) global batch; split into ``n_microbatches`` along
        axis 0, streamed through the stages, reassembled to (B, ...).

    Differentiable end-to-end: wrap in jax.value_and_grad for pipelined
    training. Compose with data parallelism by passing ``batch_axis``.
    """
    from jax.sharding import PartitionSpec as P

    S = mesh.shape[axis]
    M = int(n_microbatches)
    if M < 1:
        raise ValueError("n_microbatches must be >= 1")
    n_stages = {leaf.shape[0] for leaf in jax.tree.leaves(stacked_params)}
    if n_stages != {S}:
        raise ValueError(
            "stacked_params lead with %s stages but mesh axis '%s' has "
            "%d devices — they must match (one stage per pp coordinate); "
            "a multiple would silently drop stages" % (
                sorted(n_stages), axis, S))
    B = x.shape[0]
    local_b = B // mesh.shape[batch_axis] if batch_axis else B
    if B % (mesh.shape[batch_axis] if batch_axis else 1) or local_b % M:
        raise ValueError(
            "per-shard batch %d (global %d over %d-way '%s') not "
            "divisible by %d microbatches"
            % (local_b, B, mesh.shape[batch_axis] if batch_axis else 1,
               batch_axis, M))

    param_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    x_spec = P(batch_axis)
    out_spec = P(batch_axis)

    def local(params, xl):
        # params: stage-local pytree (leading axis 1 slice, squeezed)
        params = jax.tree.map(lambda p: p[0], params)
        rank = lax.axis_index(axis)
        micro = xl.reshape((M, xl.shape[0] // M) + xl.shape[1:])
        mshape = micro.shape[1:]

        # tick t: stage s computes microbatch (t - s) if 0 <= t-s < M.
        # `cur` holds the activation entering this stage this tick;
        # outputs collect at the LAST stage, which writes tick t-S+1's
        # result into slot t-S+1.
        nticks = S + M - 1
        outs0 = jnp.zeros((M,) + mshape, xl.dtype)
        cur0 = jnp.zeros(mshape, xl.dtype)
        # constants start device-invariant; mark them varying over every
        # sharded axis so the scan carry types line up (shard_map vma)
        vary_axes = tuple(a for a in (batch_axis, axis) if a)
        if hasattr(lax, "pcast"):
            cur0, outs0 = (lax.pcast(v, vary_axes, to="varying")
                           for v in (cur0, outs0))
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            cur, outs = carry
            # stage 0 ingests microbatch t (clamped; masked below)
            feed = micro[jnp.clip(t, 0, M - 1)]
            cur = jnp.where(rank == 0, feed, cur)
            live = jnp.logical_and(t - rank >= 0, t - rank < M)
            y = stage_fn(params, cur)
            y = jnp.where(live, y, cur)
            # last stage banks its finished microbatch (t - S + 1)
            slot = jnp.clip(t - S + 1, 0, M - 1)
            bank = jnp.logical_and(rank == S - 1, t - (S - 1) >= 0)
            outs = jnp.where(
                bank,
                lax.dynamic_update_index_in_dim(outs, y, slot, 0),
                outs)
            # rotate activations one hop down the pipe
            cur = lax.ppermute(y, axis, perm)
            return (cur, outs), None

        (cur, outs), _ = lax.scan(tick, (cur0, outs0),
                                  jnp.arange(nticks))
        # results were banked only on the last stage (others hold
        # zeros): one psum replicates them to every pp coordinate
        outs = lax.psum(outs, axis)
        return outs.reshape((M * mshape[0],) + mshape[1:])

    in_specs = (param_spec, x_spec)
    fn = _shard_map()(local, mesh=mesh, in_specs=in_specs,
                      out_specs=out_spec)
    return fn(stacked_params, x)
