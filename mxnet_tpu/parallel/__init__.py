"""Parallelism primitives: device meshes, shardings, collectives, and
gradient compression.

This package is the TPU-native replacement for src/kvstore's Comm hierarchy
and ps-lite transport (SURVEY.md §2.3): a ``jax.sharding.Mesh`` over
ICI/DCN with XLA collectives instead of NCCL/ZMQ.
"""
from .mesh import (make_mesh, data_parallel_mesh, batch_sharding,
                   replicated_sharding, shard_batch, current_mesh)
from .trainer import TrainStep, default_tp_rule
from .moe import switch_moe, moe_reference, init_moe_params
from .pipeline import pipeline_apply, stack_stage_params
from .ring_attention import (attention_reference, ring_attention,
                             ulysses_attention)

__all__ = ["make_mesh", "data_parallel_mesh", "batch_sharding",
           "replicated_sharding", "shard_batch", "current_mesh",
           "TrainStep", "default_tp_rule", "attention_reference",
           "ring_attention", "ulysses_attention",
           "pipeline_apply", "stack_stage_params",
           "switch_moe", "moe_reference", "init_moe_params"]
