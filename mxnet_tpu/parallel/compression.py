"""2-bit gradient compression with error feedback.

Reference parity: src/kvstore/gradient_compression.h:37-138 and
gradient_compression-inl.h (rahul003's contribution). Semantics:

  residual += grad
  q = +threshold where residual >  threshold
      -threshold where residual < -threshold
      0 otherwise
  residual -= q          (error feedback)

The reference packs 16 2-bit codes per float for the wire; on TPU the
compress→decompress pair fuses into one XLA kernel. For the cross-host
(DCN) path, ``compress``/``decompress`` pack 4 2-bit codes per byte with
plain jnp bit ops — XLA fuses the shift/or chain into one kernel, so a
hand-written Pallas kernel buys nothing here.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["TwoBitCompressor"]


class TwoBitCompressor:
    """All three entry points are jitted with ``self`` static; equality/
    hash are defined on the threshold alone so every compressor with the
    same config shares one compile-cache entry — N kvstores (or N
    re-creations across steps) never retrace. ``_traces`` counts actual
    traces (it only increments while JAX traces a method body), which the
    regression test in tests/test_kvstore_fused.py pins flat across
    steps."""

    _traces = 0

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)

    def __eq__(self, other):
        return (type(other) is type(self)
                and other.threshold == self.threshold)

    def __hash__(self):
        return hash((type(self).__name__, self.threshold))

    # analyze: ok(retrace) static_argnums quantize helper compiles once per compressor config; parity pinned by test_parallel
    @functools.partial(jax.jit, static_argnums=0)
    def compress_decompress(self, grad, residual):
        """Returns (quantized_grad, new_residual) — the fused local form
        used by single-process kvstores (comm.h usage in the reference)."""
        TwoBitCompressor._traces += 1
        t = jnp.asarray(self.threshold, dtype=grad.dtype)
        acc = residual + grad
        q = jnp.where(acc > t, t, jnp.where(acc < -t, -t, jnp.zeros_like(acc)))
        return q, acc - q

    # analyze: ok(retrace) static_argnums dequantize helper compiles once per compressor config; parity pinned by test_parallel
    @functools.partial(jax.jit, static_argnums=0)
    def compress(self, grad, residual):
        """Returns (packed_uint8, new_residual): 4 2-bit codes per byte —
        the wire format for cross-host (DCN) pushes. Code: 0 = zero,
        1 = +threshold, 2 = -threshold (reference -inl.h quantize_2bit)."""
        TwoBitCompressor._traces += 1
        t = jnp.asarray(self.threshold, dtype=grad.dtype)
        acc = residual + grad
        code = jnp.where(acc > t, 1, jnp.where(acc < -t, 2, 0)).astype(jnp.uint8)
        q = jnp.where(code == 1, t, jnp.where(code == 2, -t, 0)).astype(grad.dtype)
        flat = code.reshape(-1)
        pad = (-flat.shape[0]) % 4
        flat = jnp.pad(flat, (0, pad))
        flat = flat.reshape(-1, 4)
        packed = (flat[:, 0] | (flat[:, 1] << 2) | (flat[:, 2] << 4)
                  | (flat[:, 3] << 6))
        return packed, acc - q

    def decompress(self, packed, shape, dtype=jnp.float32):
        return self._decompress(packed, tuple(shape), dtype)

    # analyze: ok(retrace) static_argnums error-feedback helper compiles once per compressor config; parity pinned by test_parallel
    @functools.partial(jax.jit, static_argnums=(0, 2, 3))
    def _decompress(self, packed, shape, dtype):
        TwoBitCompressor._traces += 1
        t = jnp.asarray(self.threshold, dtype=dtype)
        codes = jnp.stack([packed & 3, (packed >> 2) & 3, (packed >> 4) & 3,
                           (packed >> 6) & 3], axis=-1).reshape(-1)
        n = 1
        for s in shape:
            n *= s
        codes = codes[:n]
        vals = jnp.where(codes == 1, t, jnp.where(codes == 2, -t, 0))
        return vals.reshape(shape).astype(dtype)
