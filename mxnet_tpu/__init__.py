"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Brand-new implementation (not a port): the compute path is JAX/XLA/Pallas,
scheduling and memory are XLA's, and distribution is ``jax.sharding`` over
device meshes. See SURVEY.md for the capability map against the reference
(Apache MXNet ~1.2, rahul003 fork).

Usage mirrors MXNet::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    net = mx.sym.FullyConnected(mx.sym.Variable('data'), num_hidden=10)
"""
__version__ = "0.1.0"


def _maybe_init_distributed():
    """When spawned by tools/launch.py (reference DMLC env) or
    tools/run_multihost.py (MXTPU_NUM_PROCESSES env, the kvstore='tpu'
    contract — see kvstore_tpu/dist.py), join the collective world
    BEFORE anything touches the XLA backend (jax.distributed.initialize
    must run first). The reference does the analogous bootstrap on
    import: a DMLC_ROLE=server process enters the ps-lite server loop
    from python/mxnet/kvstore_server.py.

    DELIBERATE duplication of kvstore_tpu/dist.initialize_from_env:
    this must run before ANY heavy import (importing kvstore_tpu pulls
    jax.numpy/ndarray, touching the XLA backend we must precede), so
    the env contract is restated here — keep the two in sync."""
    import os
    is_worker = os.environ.get("DMLC_ROLE") == "worker"
    n_tpu = int(os.environ.get("MXTPU_NUM_PROCESSES", "0") or 0)
    if not is_worker and n_tpu <= 1:
        return
    n = n_tpu if n_tpu > 1 else int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if n <= 1:
        return
    uri = os.environ.get("MXTPU_COORDINATOR")
    if uri is None:
        root = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT")
        uri = "%s:%s" % (root, port) if root and port else None
    if uri is None:
        # same contract as dist.initialize_from_env: a promised world
        # with no coordinator must fail HERE, before the XLA backend is
        # live, not later at kvstore creation with a weaker message
        raise ImportError(
            "distributed worker env found (num processes %d) but no "
            "coordinator address (MXTPU_COORDINATOR=host:port, or "
            "DMLC_PS_ROOT_URI/_PORT). Launch workers via "
            "tools/run_multihost.py or tools/launch.py, which set the "
            "whole contract." % n)
    rank = os.environ.get("MXTPU_PROCESS_ID")
    if rank is None:
        rank = os.environ.get("MXTPU_WORKER_RANK")
    if rank is None:
        raise ImportError(
            "distributed worker env found (num processes %d) but no rank "
            "(MXTPU_PROCESS_ID / MXTPU_WORKER_RANK). Launch workers via "
            "tools/run_multihost.py or tools/launch.py — a collective "
            "world needs ranks pinned at spawn (ps-lite assigned them "
            "dynamically)." % n)
    import jax
    jax.distributed.initialize(uri, num_processes=n, process_id=int(rank))
    # keep this process' eager/jit results on its own devices: without a
    # default device, multi-controller jit replicates outputs across the
    # whole world and host reads (asnumpy) of them fail
    jax.config.update("jax_default_device", jax.local_devices()[0])


_maybe_init_distributed()

from .base import MXNetError
from .context import (Context, cpu, gpu, tpu, cpu_pinned, current_context,
                      num_gpus, num_tpus)
from . import random
from . import name
from . import autograd
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol
from . import symbol as sym
from .symbol import Symbol, AttrScope
from . import executor
from .executor import Executor
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import callback
from . import io
from .io import DataBatch, DataIter
from . import kvstore
from . import kvstore as kv
from .kvstore import KVStore
from . import model
from . import module
from . import module as mod
from .module import Module
from . import parallel
from . import sharding
from . import models
from . import gluon
from . import recordio
from . import image
from . import operator
from . import visualization
from . import viz
from . import contrib
from . import rnn
from . import rtc
from . import config
from . import predictor
from . import serving
from . import decode
from . import fleet
from . import profiler
from . import telemetry
from . import pallas
from . import aot
from . import checkpoint
from . import embedding
from . import kvstore_tpu
from . import monitor
from .monitor import Monitor
from . import test_utils

# server/scheduler-role processes enter their loop here, at the END of
# the package import (reference wires kvstore_server the same way,
# python/mxnet/__init__.py:57). It must NOT run mid-import: the serve
# loop would hold the package's import lock forever and any handler
# thread importing a submodule (optimizer, compression) would deadlock.
from . import kvstore_server  # noqa: E402,F401
kvstore_server._init_kvstore_server_module()
