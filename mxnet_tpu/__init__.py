"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Brand-new implementation (not a port): the compute path is JAX/XLA/Pallas,
scheduling and memory are XLA's, and distribution is ``jax.sharding`` over
device meshes. See SURVEY.md for the capability map against the reference
(Apache MXNet ~1.2, rahul003 fork).

Usage mirrors MXNet::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    net = mx.sym.FullyConnected(mx.sym.Variable('data'), num_hidden=10)
"""
__version__ = "0.1.0"

from .base import MXNetError
from .context import (Context, cpu, gpu, tpu, cpu_pinned, current_context,
                      num_gpus, num_tpus)
from . import random
from . import autograd
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol
from . import symbol as sym
from .symbol import Symbol, AttrScope
from . import executor
from .executor import Executor
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import callback
from . import io
from .io import DataBatch, DataIter
from . import kvstore
from . import kvstore as kv
from .kvstore import KVStore
from . import model
from . import module
from . import module as mod
from .module import Module
from . import parallel
from . import models
from . import gluon
from . import recordio
from . import image
from . import profiler
from . import monitor
from .monitor import Monitor
from . import test_utils
