"""mx.sharding — GSPMD model parallelism through Symbol/Gluon.

The fused fit step already psums gradients over a 1-D ``dp`` mesh
(module/executor_group.py).  This package generalizes the mesh to 2-D
(data x model) and lets users annotate *which* axis each parameter or
activation is partitioned over, using the same string-attr machinery
that carries ``lr_mult`` through Symbol/Gluon:

    mx.sharding.set_mesh({"dp": 4, "mp": 2})          # or MXTPU_MESH=dp=4,mp=2
    w = mx.sym.Variable("fc_weight", __sharding__=mx.sharding.spec("mp", None))
    y = mx.sharding.constrain(y, None, None, "mp")    # activation constraint

At bind time the executor resolves ``__sharding__`` attrs into
``jax.sharding.NamedSharding``s and places the parameters sharded (the
HBM census shows per-device param bytes shrink); inside the one jitted
program every annotated activation gets a
``jax.lax.with_sharding_constraint`` so GSPMD partitions the matmuls
over ``mp`` while the gradient psum spans ``dp`` only — still one
launch per step, zero steady-state retraces.

Specs are serialized as canonical tuple reprs (e.g. ``"('mp', None)"``)
because Symbol attrs are strings and must survive tojson/pickle
round-trips (see docs/SHARDING.md).
"""
from __future__ import annotations

import ast
import os
import time

import numpy as _np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..parallel import mesh as _mesh_mod
from .. import telemetry as _telemetry

__all__ = [
    "KNOWN_AXES", "SHARDING_ATTR",
    "spec", "parse_spec", "partition_spec",
    "set_mesh", "get_mesh", "clear_mesh", "mesh_fingerprint",
    "resolve", "check_divisible", "match_param",
    "annotate", "constrain", "collect_var_specs", "symbol_has_sharding",
    "active_fingerprint",
    "column_parallel_fc", "row_parallel_fc", "ring_attention_on_mesh",
    "per_device_param_bytes",
]

#: Mesh axis names the framework knows about (parallel/mesh.py docs):
#: dp=data, mp/tp=tensor (model), pp=pipeline, sp=sequence, ep=expert.
KNOWN_AXES = ("dp", "mp", "tp", "pp", "sp", "ep")

#: The Symbol/Parameter string attr carrying a serialized spec.
SHARDING_ATTR = "__sharding__"

# -- telemetry (names must stay literal for the analyze telemetry pass) -
CONSTRAINT_SITES = _telemetry.REGISTRY.gauge(
    "sharding_constraint_sites",
    help="with_sharding_constraint sites in the most recently built "
         "compiled program", unit="sites")
RESOLVE_MS = _telemetry.REGISTRY.histogram(
    "sharding_resolve_ms",
    help="bind-time latency resolving __sharding__ attrs to "
         "NamedShardings", unit="ms")

# The explicitly selected training mesh.  Kept separate from
# parallel.mesh._CURRENT because data_parallel_mesh() overwrites that
# slot on every Module bind; this one changes only via set_mesh()/env.
_STATE = {"mesh": None, "env_checked": False}


# ----------------------------------------------------------------------
# specs
# ----------------------------------------------------------------------
def spec(*axes):
    """Serialize a per-dim partition spec to its canonical attr string.

    ``spec('mp', None)`` -> ``"('mp', None)"`` — dim 0 split over the
    ``mp`` mesh axis, dim 1 replicated.  An entry may also be a tuple of
    axis names (multi-axis sharding of one dim).  Unnamed trailing dims
    are replicated, matching ``jax.sharding.PartitionSpec``.
    """
    canon = []
    for a in axes:
        if a is None:
            canon.append(None)
        elif isinstance(a, str):
            _check_axis_name(a)
            canon.append(a)
        elif isinstance(a, (tuple, list)):
            for x in a:
                _check_axis_name(x)
            canon.append(tuple(a))
        else:
            raise MXNetError("sharding.spec entries must be an axis "
                             "name, None, or a tuple of axis names; got "
                             "%r" % (a,))
    return repr(tuple(canon))


def _check_axis_name(a):
    if not isinstance(a, str) or a not in KNOWN_AXES:
        raise MXNetError(
            "unknown mesh axis %r (known axes: %s)" % (a, ", ".join(KNOWN_AXES)))


def parse_spec(s):
    """Inverse of :func:`spec`: attr string -> tuple of axis entries."""
    if isinstance(s, tuple):
        return s
    try:
        val = ast.literal_eval(s)
    except (ValueError, SyntaxError):
        raise MXNetError("malformed __sharding__ attr %r" % (s,))
    if not isinstance(val, tuple):
        raise MXNetError("__sharding__ attr must serialize a tuple, got %r"
                         % (s,))
    for a in val:
        if a is None:
            continue
        if isinstance(a, str):
            _check_axis_name(a)
        elif isinstance(a, tuple):
            for x in a:
                _check_axis_name(x)
        else:
            raise MXNetError("malformed __sharding__ entry %r in %r"
                             % (a, s))
    return val


def partition_spec(s):
    """Attr string -> ``jax.sharding.PartitionSpec``."""
    return P(*parse_spec(s))


# ----------------------------------------------------------------------
# mesh selection
# ----------------------------------------------------------------------
def set_mesh(axes=None, devices=None):
    """Select the training mesh.

    ``set_mesh({'dp': 4, 'mp': 2})`` builds a 2-D mesh over the first 8
    visible devices (row-major, so adjacent devices share an ``mp``
    group).  ``set_mesh(mesh)`` adopts an existing ``jax.sharding.Mesh``;
    ``set_mesh(None)`` clears the selection (modules fall back to the
    implicit 1-D dp mesh).  Returns the mesh (or None).
    """
    if axes is None:
        _STATE["mesh"] = None
        _STATE["env_checked"] = True       # explicit clear beats the env
        return None
    if isinstance(axes, Mesh):
        mesh = axes
    else:
        for name in axes:
            _check_axis_name(name)
        mesh = _mesh_mod.make_mesh(dict(axes), devices=devices)
    _STATE["mesh"] = mesh
    _STATE["env_checked"] = True
    _mesh_mod._CURRENT["mesh"] = mesh
    return mesh


def _mesh_from_env():
    raw = os.environ.get("MXTPU_MESH", "").strip()
    if not raw:
        return None
    axes = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MXNetError("MXTPU_MESH entries must look like dp=4; "
                             "got %r" % part)
        name, _, size = part.partition("=")
        name = name.strip()
        _check_axis_name(name)
        axes[name] = int(size)
    if not axes:
        return None
    return set_mesh(axes)


def get_mesh():
    """The explicitly selected mesh, lazily parsing ``MXTPU_MESH`` the
    first time (format ``dp=4,mp=2``). None when no mesh is selected."""
    if _STATE["mesh"] is None and not _STATE["env_checked"]:
        _STATE["env_checked"] = True
        _mesh_from_env()
    return _STATE["mesh"]


def clear_mesh():
    """Drop the selected mesh (and suppress MXTPU_MESH re-parsing)."""
    return set_mesh(None)


def mesh_fingerprint(mesh):
    """Stable hashable identity of a mesh: axis names/sizes + devices.
    Used to key compiled-program caches so a mesh change retraces
    instead of reusing programs built against stale shardings."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names),
            tuple(int(s) for s in mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat))


# ----------------------------------------------------------------------
# resolution (bind time)
# ----------------------------------------------------------------------
def check_divisible(entries, shape, mesh, what=""):
    """Raise unless every named axis divides its dim of ``shape``."""
    if len(entries) > len(shape):
        raise MXNetError(
            "sharding spec %r has %d entries but %s%r has rank %d"
            % (entries, len(entries), what and what + " ", tuple(shape),
               len(shape)))
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    for dim, entry in enumerate(entries):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for a in axes:
            if a not in sizes:
                raise MXNetError(
                    "sharding spec %r names axis %r absent from mesh %s"
                    % (entries, a, tuple(mesh.axis_names)))
            n *= int(sizes[a])
        if shape[dim] % n != 0:
            raise MXNetError(
                "sharding spec %r: axis group %r (size %d) cannot divide "
                "dim %d of %s%r" % (entries, entry, n, dim,
                                    what and what + " ", tuple(shape)))


def resolve(spec_str, shape, mesh, what=""):
    """Attr string + shape + mesh -> validated ``NamedSharding``.

    Bind-time latency lands in the ``sharding_resolve_ms`` histogram.
    """
    t0 = time.perf_counter()
    try:
        entries = parse_spec(spec_str)
        check_divisible(entries, shape, mesh, what=what)
        return NamedSharding(mesh, P(*entries))
    finally:
        RESOLVE_MS.observe((time.perf_counter() - t0) * 1000.0)


def match_param(leaf, param_data, mesh=None):
    """Place an optimizer-state / residual leaf with its parameter's
    sharding (same-shape leaves inherit it; scalars and mismatched
    shapes are replicated over the same mesh so every input of the
    donated fit program lives on one device set)."""
    sh = getattr(param_data, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return leaf
    if tuple(getattr(leaf, "shape", ())) == tuple(param_data.shape):
        return jax.device_put(leaf, sh)
    return jax.device_put(leaf, NamedSharding(sh.mesh, P()))


# ----------------------------------------------------------------------
# symbol annotation
# ----------------------------------------------------------------------
def annotate(symbol, *axes):
    """Attach ``spec(*axes)`` to a symbol head node (a Variable for
    parameter placement, any op output for an activation constraint).
    Returns the same symbol for chaining."""
    symbol._set_attr(**{SHARDING_ATTR: spec(*axes)})
    return symbol


# activation alias — reads as jax.lax.with_sharding_constraint at the
# symbol level
constrain = annotate


def collect_var_specs(symbol):
    """{node name: spec string} for every annotated node in the graph,
    variables and op outputs alike."""
    out = {}
    for node in symbol._topo():
        s = node.str_attrs.get(SHARDING_ATTR)
        if s:
            out[node.name] = s
    return out


def symbol_has_sharding(symbol):
    for node in symbol._topo():
        if node.str_attrs.get(SHARDING_ATTR):
            return True
    return False


def active_fingerprint(symbol):
    """Cache key component for compiled programs: the selected mesh's
    fingerprint when this symbol carries sharding annotations (those
    programs close over the mesh), else None (mesh-independent)."""
    mesh = get_mesh()
    if mesh is None or not symbol_has_sharding(symbol):
        return None
    return mesh_fingerprint(mesh)


# ----------------------------------------------------------------------
# tensor-parallel building blocks (Megatron-style, (out, in) weights)
# ----------------------------------------------------------------------
def column_parallel_fc(data, num_hidden, name, axis="mp", no_bias=False,
                       flatten=False, act_spec=None, **kwargs):
    """FullyConnected whose OUTPUT features are split over ``axis``:
    weight (out, in) sharded ``(axis, None)``, bias ``(axis,)``.  The
    activation keeps the split (annotate with ``act_spec`` — e.g.
    ``(None, None, 'mp')`` for (B, S, F) inputs) and feeds a row-parallel
    layer with no communication in between."""
    from .. import symbol as sym
    weight = sym.Variable(name + "_weight",
                          **{SHARDING_ATTR: spec(axis, None)})
    bias = None if no_bias else sym.Variable(
        name + "_bias", **{SHARDING_ATTR: spec(axis)})
    out = sym.FullyConnected(data=data, weight=weight, bias=bias,
                             num_hidden=num_hidden, no_bias=no_bias,
                             flatten=flatten, name=name, **kwargs)
    if act_spec is not None:
        constrain(out, *act_spec)
    return out


def row_parallel_fc(data, num_hidden, name, axis="mp", no_bias=False,
                    flatten=False, **kwargs):
    """FullyConnected whose INPUT features arrive split over ``axis``:
    weight (out, in) sharded ``(None, axis)``; the output is constrained
    replicated, which is where GSPMD inserts the partial-sum
    all-reduce.  Bias stays replicated (added once, after the psum)."""
    from .. import symbol as sym
    weight = sym.Variable(name + "_weight",
                          **{SHARDING_ATTR: spec(None, axis)})
    bias = None if no_bias else sym.Variable(name + "_bias")
    out = sym.FullyConnected(data=data, weight=weight, bias=bias,
                             num_hidden=num_hidden, no_bias=no_bias,
                             flatten=flatten, name=name, **kwargs)
    return constrain(out)


def ring_attention_on_mesh(q, k, v, axis="sp", causal=False, scale=None,
                           batch_axis="dp"):
    """Run parallel.ring_attention over the selected mesh (jnp arrays,
    (B, T, H, D)).  The mesh must carry ``axis``; ``batch_axis`` is used
    when present so dp x sp meshes work unchanged."""
    from ..parallel.ring_attention import ring_attention as _ring
    mesh = get_mesh()
    if mesh is None:
        raise MXNetError("ring_attention_on_mesh: no mesh selected "
                         "(call mx.sharding.set_mesh or set MXTPU_MESH)")
    if axis not in mesh.axis_names:
        raise MXNetError("ring_attention_on_mesh: mesh %s has no %r axis"
                         % (tuple(mesh.axis_names), axis))
    b = batch_axis if batch_axis in mesh.axis_names else None
    return _ring(q, k, v, mesh, axis=axis, causal=causal, scale=scale,
                 batch_axis=b)


# ----------------------------------------------------------------------
# HBM accounting
# ----------------------------------------------------------------------
def per_device_param_bytes(arrays, device=None):
    """Bytes the given arrays occupy on ONE device (the first mesh /
    visible device by default).  Replicated arrays count full size;
    mp-sharded ones count their shard only — this is the number the
    ``param_bytes_per_device`` census gauge reports."""
    total = 0
    for a in arrays:
        data = getattr(a, "_data", a)
        shards = getattr(data, "addressable_shards", None)
        if not shards:
            total += int(getattr(data, "nbytes", 0))
            continue
        dev = device if device is not None else shards[0].device
        for s in shards:
            if s.device == dev:
                total += int(s.data.nbytes)
    return total
