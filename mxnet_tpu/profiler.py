"""mx.profiler — profiling with chrome://tracing output over jax.profiler.

Reference parity: python/mxnet/profiler.py:28-127 (set_config / set_state /
pause / resume / dump / dumps) and the user-definable objects (Domain, Task,
Frame, Counter, Marker) from src/profiler/profiler.h. Two layers:

* **Host events** — eager op dispatch (profile_imperative), executor
  forward/backward spans (profile_symbolic), and user Task/Frame/Counter/
  Marker objects are recorded host-side and dumped as chrome://tracing JSON
  to ``filename``, exactly like the reference's profiler output format
  (src/profiler/profiler.h:87,437). Host spans measure *dispatch* time —
  XLA executes asynchronously, so a span closes when the op is enqueued,
  not when the device finishes (the reference's engine instrumented actual
  kernel completion; XLA hides that from the host).
* **Device timeline** — when ``trace_dir`` is set, start()/stop() also run
  ``jax.profiler.start_trace``/``stop_trace``, producing an xplane/perfetto
  trace with real per-kernel device timing (the TPU-native replacement for
  the reference's per-op GPU stats; view with tensorboard or perfetto).

Env autostart parity: MXNET_PROFILER_AUTOSTART=1 (docs/faq/env_var.md:131).
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time

__all__ = ["set_config", "set_state", "start", "stop", "pause", "resume",
           "dump", "dumps", "state", "Domain", "Task", "Frame", "Counter",
           "Marker", "scope"]

_lock = threading.Lock()
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    "trace_dir": None,          # xplane/perfetto device trace output dir
    "continuous_dump": False,
}
_state = "stop"         # 'run' | 'stop' (pause() => 'pause')
_events = []            # chrome trace events
_aggregate = {}         # name -> [count, total_us, min_us, max_us]
_epoch = time.perf_counter()
_device_trace_on = False

# fast-path flags consulted by the dispatch/executor hooks
IMPERATIVE_ON = False
SYMBOLIC_ON = False


def _now_us():
    return (time.perf_counter() - _epoch) * 1e6


def _refresh_flags():
    global IMPERATIVE_ON, SYMBOLIC_ON
    running = _state == "run"
    IMPERATIVE_ON = running and (_config["profile_imperative"]
                                 or _config["profile_all"])
    SYMBOLIC_ON = running and (_config["profile_symbolic"]
                               or _config["profile_all"])


def set_config(**kwargs):
    """Configure the profiler (reference profiler.py set_config). Accepts
    the reference kwargs plus ``trace_dir`` for the device xplane trace.

    Setting ``trace_dir`` while the profiler is already running (or
    paused — pause never ends the device trace) starts the device
    xplane trace IMMEDIATELY; it used to silently wait for the next
    stop/start cycle."""
    import logging
    global _device_trace_on
    with _lock:
        # _config is read by every profiled dispatch on other threads;
        # writes hold the module lock (mx.analyze threads pass)
        for k, v in kwargs.items():
            if k not in _config:
                # reference-valid options we don't distinguish (e.g.
                # profile_process='worker'|'server') are accepted with
                # a note
                logging.warning("profiler.set_config: option '%s' is "
                                "accepted but has no effect here", k)
                continue
            _config[k] = v
    _refresh_flags()
    if _state in ("run", "pause") and _config["trace_dir"]:
        if not _device_trace_on:
            import jax
            jax.profiler.start_trace(_config["trace_dir"])
            _device_trace_on = True
        elif "trace_dir" in kwargs:
            logging.warning(
                "profiler.set_config: a device trace is already running; "
                "the new trace_dir takes effect at the next stop/start "
                "cycle")


def state():
    return _state


def set_state(new_state="stop"):
    """'run' or 'stop' (reference profiler.py set_state)."""
    global _state, _device_trace_on
    if new_state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if new_state == _state:
        return
    _state = new_state
    _refresh_flags()
    if new_state == "run" and _config["trace_dir"] and not _device_trace_on:
        import jax
        jax.profiler.start_trace(_config["trace_dir"])
        _device_trace_on = True
    elif new_state == "stop":
        if _device_trace_on:
            import jax
            jax.profiler.stop_trace()
            _device_trace_on = False
        if _config["continuous_dump"]:
            dump(finished=False)


def start():
    set_state("run")


def stop():
    set_state("stop")


def pause():
    """Suspend host-event recording without ending the device trace."""
    global _state
    if _state == "run":
        _state = "pause"
        _refresh_flags()


def resume():
    global _state
    if _state == "pause":
        _state = "run"
        _refresh_flags()


def add_event(name, cat, ts_us, dur_us, tid=None, args=None, ph="X"):
    if _state != "run":
        # nothing is recorded while stopped/paused — user Counter/Task
        # objects may outlive the profiled window without leaking events
        return
    ev = {"name": name, "cat": cat, "ph": ph, "ts": ts_us,
          "pid": os.getpid(),
          "tid": tid if tid is not None else threading.get_ident() & 0xFFFF}
    if ph == "X":
        ev["dur"] = dur_us
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)
        if _config["aggregate_stats"] and ph == "X":
            st = _aggregate.setdefault(name, [0, 0.0, float("inf"), 0.0])
            st[0] += 1
            st[1] += dur_us
            st[2] = min(st[2], dur_us)
            st[3] = max(st[3], dur_us)


class scope:
    """Context manager recording one chrome-trace span."""

    def __init__(self, name, cat="operator"):
        self.name, self.cat = name, cat

    def __enter__(self):
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc):
        add_event(self.name, self.cat, self._t0, _now_us() - self._t0)
        return False


def record_op(name, t0_us, t1_us):
    add_event(name, "operator", t0_us, t1_us - t0_us)


def dump(finished=True):
    """Write the chrome-trace JSON to ``filename`` (reference dump()).

    Non-empty dumps also carry closing counter-track samples of every
    mx.telemetry registry series (telemetry/chrome.py), so host metrics
    line up with the trace without a separate scrape."""
    with _lock:
        events = list(_events)
        if finished:
            _events.clear()
    if events:
        try:
            from .telemetry import chrome as _tchrome
            events.extend(_tchrome.dump_events())
        except Exception:
            pass
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(_config["filename"], "w") as f:
        json.dump(doc, f)


def dumps(reset=False):
    """Return the aggregate-stats table as a string (reference dumps();
    requires set_config(aggregate_stats=True))."""
    with _lock:
        rows = sorted(_aggregate.items(), key=lambda kv: -kv[1][1])
        if reset:
            _aggregate.clear()
    lines = ["%-40s %8s %12s %12s %12s %12s" %
             ("Name", "Calls", "Total(us)", "Avg(us)", "Min(us)", "Max(us)")]
    for name, (cnt, tot, mn, mx) in rows:
        lines.append("%-40s %8d %12.1f %12.1f %12.1f %12.1f" %
                     (name[:40], cnt, tot, tot / max(cnt, 1), mn, mx))
    return "\n".join(lines)


# ----------------------------------------------------------------------
# user-definable profiler objects (reference src/profiler/profiler.h
# ProfileTask/ProfileFrame/ProfileCounter/ProfileMarker)
# ----------------------------------------------------------------------
class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(name, self)

    def new_frame(self, name):
        return Frame(name, self)

    def new_counter(self, name, value=None, vital=False):
        """``vital=True`` marks a pinned correctness witness: its
        registry series keeps counting through ``telemetry.disable()``
        (which otherwise no-ops every instrument)."""
        return Counter(self, name, value, vital=vital)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    _cat = "task"

    def __init__(self, name, domain=None):
        self.name = name
        self.domain = domain
        self._t0 = None

    def start(self):
        self._t0 = _now_us()

    def stop(self):
        if self._t0 is None:
            return
        cat = self.domain.name if self.domain else self._cat
        add_event(self.name, cat, self._t0, _now_us() - self._t0)
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class Task(_Span):
    _cat = "task"


class Frame(_Span):
    _cat = "frame"


class Counter:
    """Thread-safe: serving replicas and user threads may bump the same
    counter concurrently (reference ProfileCounter is atomic too,
    src/profiler/profiler.h).

    Storage lives in the mx.telemetry registry (a Gauge — profiler
    counters allow set/decrement): ``Domain.new_counter(name)`` is now
    a live VIEW over ``telemetry.REGISTRY`` series ``name`` (dots map
    to underscores), so ``DEVICE_DISPATCHES``/``HOST_SYNCS``/the
    kvstore counters show up in ``GET /metrics`` and the flight
    recorder while ``.value`` and chrome-trace emission behave exactly
    as before. Two Counters with one name share one series."""

    def __init__(self, domain, name, value=None, vital=False):
        self.domain, self.name = domain, name
        self._vlock = threading.Lock()
        from . import telemetry as _tm
        self._metric = _tm.REGISTRY.gauge(
            name, "profiler counter (domain %s)"
            % (domain.name if domain else "counter"), vital=vital)
        if value is not None:
            self._emit(self._metric.set(value))

    @property
    def value(self):
        return self._metric.value

    def _emit(self, value):
        add_event(self.name, self.domain.name if self.domain else "counter",
                  _now_us(), 0, ph="C", args={self.name: value})

    # _emit stays inside the lock so trace samples record in value order
    # (an emit outside would let a stale value land last in the trace);
    # add_event's module lock never takes _vlock, so no ordering cycle
    def set_value(self, value):
        with self._vlock:
            self._emit(self._metric.set(value))

    def increment(self, delta=1):
        with self._vlock:
            self._emit(self._metric.inc(delta))

    def decrement(self, delta=1):
        with self._vlock:
            self._emit(self._metric.dec(delta))

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


# global device-launch witness (docs/TRAINING.md): every compiled-program
# dispatch on the training hot path increments this counter — executor
# fwd / fused fwd+bwd launches, kvstore bucket programs, and the fused
# fit-step program. bench.py --mode train reads deltas to report
# train_dispatches_per_step independent of wall clock.
DEVICE_DISPATCHES = Domain("device").new_counter("device_dispatches",
                                                 vital=True)


class Marker:
    def __init__(self, domain, name):
        self.domain, self.name = domain, name

    def mark(self, scope="process"):
        add_event(self.name, self.domain.name if self.domain else "marker",
                  _now_us(), 0, ph="i",
                  args={"scope": scope})


if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    set_state("run")
    atexit.register(dump)
