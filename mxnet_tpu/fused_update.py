"""Shared fused optimizer-update builder (docs/TRAINING.md).

An optimizer *describes* its update as a pure jittable program instead
of opting in per engine: ``Optimizer._fused_sig()`` returns a hashable
``(kind, *hypers)`` tuple that fully determines the per-key update
math, and this module turns that tuple into the program pieces all
three compiled consumers share — the flat-bucket kvstore step
(kvstore_fused.py), the cross-host bucket step (kvstore_tpu/engine.py)
and the per-tree single-launch fit step (module/fused_fit.py). Because
there is ONE builder, an optimizer fused here is fused everywhere, and
the eager ops in ops/optimizer_ops.py remain the parity oracle for all
of them (tests/test_fused_optimizers.py pins the matrix).

Contract for a kind's ``apply(w32, g, inner, lr, wd, rescale, extra,
use_wd)``:

* ``w32`` is the f32 view of the weight (the f32 master copy when the
  key is multi-precision, else the weight cast to f32);
* ``g`` is the raw f32 reduced gradient — each kind owns its full
  gradient pipeline (rescale -> clip -> wd in whatever order its eager
  op uses) so parity is exact, not approximate;
* ``inner`` is the optimizer state in its natural nested structure
  (None / array / tuple) and the same structure must come back;
* ``lr``/``wd``/``rescale`` and the per-key ``extra`` scalars are
  RUNTIME values (never trace keys): lr schedules, per-key bias
  correction, ragged-batch rescale rewrites and loss-scale changes
  never retrace;
* ``use_wd`` is the one static flag (mirrors the eager ops' host-side
  ``if wd:`` short-circuit).

Multi-precision ``(inner_state, weight32)`` state tuples are handled
by the shared wrapper (:func:`apply_one`): the master weight is peeled
off the state, the update runs in f32, and the low-precision model
weight is refreshed by a cast — all inside the same donated program.

This module also owns :class:`DynamicLossScaler` — bf16/f16 training's
loss-scale state (scale, good-step count, overflow skips) lives ON
DEVICE and is donated through the fused fit program; overflow
detection and the skip-update decision are a ``lax.cond`` inside the
program, and telemetry (the ``loss_scale`` gauge and the
``loss_scale_overflow_skips`` counter) is published lazily at sync
boundaries, so a steady-state step still has zero host syncs.
"""
from __future__ import annotations

import os

import numpy as _np
import jax.numpy as jnp

from . import telemetry as _telemetry

__all__ = ["build", "supported", "apply_one", "bulk_apply",
           "flatten_state", "state_template", "unflatten",
           "DynamicLossScaler", "scaler_config", "LOW_PRECISION"]

# the low-precision dtypes that get f32 master weights under
# multi_precision and are eligible for loss scaling
LOW_PRECISION = (_np.dtype("float16"), _np.dtype("bfloat16"))


def is_low_precision(dtype):
    return _np.dtype(dtype) in LOW_PRECISION


# ----------------------------------------------------------------------
# state flattening: optimizer state -> ordered leaves + hashable template
# ----------------------------------------------------------------------
def flatten_state(state):
    """Flatten a nested optimizer state (tuples / arrays / None) into
    ``(leaves, template)``: ``leaves`` is the ordered list of array
    leaves (NDArrays on the host side, jax arrays in-program) and
    ``template`` is a hashable structure descriptor — ``None`` for an
    absent leaf, ``"a"`` for an array, ``("t", ...)`` for a tuple.
    The template is part of every engine's program cache key."""
    if state is None:
        return [], None
    if isinstance(state, tuple):
        leaves, tpls = [], []
        for s in state:
            sub, t = flatten_state(s)
            leaves.extend(sub)
            tpls.append(t)
        return leaves, ("t",) + tuple(tpls)
    return [state], "a"


def state_template(state):
    return flatten_state(state)[1]


def unflatten(tpl, leaves):
    """Inverse of :func:`flatten_state`: rebuild the nested structure
    from the flat leaf sequence."""
    it = iter(leaves)

    def rec(t):
        if t is None:
            return None
        if t == "a":
            return next(it)
        return tuple(rec(s) for s in t[1:])
    return rec(tpl)


def _leaf_values(state, out):
    if state is None:
        return out
    if isinstance(state, tuple):
        for s in state:
            _leaf_values(s, out)
        return out
    out.append(state)
    return out


# ----------------------------------------------------------------------
# the kind registry
# ----------------------------------------------------------------------
class _FusedUpdate:
    """One kind's compiled-update descriptor: the pure per-key apply
    plus the number of per-key extra runtime scalars the optimizer's
    host hook (``Optimizer._fused_extra``) feeds it."""

    __slots__ = ("kind", "apply", "n_extra")

    def __init__(self, kind, apply, n_extra=0):
        self.kind = kind
        self.apply = apply
        self.n_extra = n_extra


_KINDS = {}
_BUILT = {}


def register_kind(kind):
    def deco(builder):
        _KINDS[kind] = builder
        return builder
    return deco


def supported(sig):
    """True when ``sig`` names a registered fused-update kind."""
    return bool(sig) and sig[0] in _KINDS


def build(sig):
    """``sig`` (an ``Optimizer._fused_sig()`` tuple) -> cached
    :class:`_FusedUpdate`. Raises KeyError for unknown kinds — engines
    gate on :func:`supported` / a None sig first."""
    upd = _BUILT.get(sig)
    if upd is None:
        upd = _BUILT[sig] = _KINDS[sig[0]](sig)
    return upd


def _clip(g, clip):
    if clip is not None and clip >= 0:
        return jnp.clip(g, -clip, clip)
    return g


def _common(g, w32, lr_unused, wd, rescale, clip, use_wd):
    """ops/optimizer_ops.py ``_apply_common``: rescale -> clip -> wd."""
    g = g * rescale
    g = _clip(g, clip)
    if use_wd:
        g = g + wd * w32
    return g


@register_kind("sgd")
def _sgd(sig):
    _, momentum, clip = sig

    def apply(w32, g, inner, lr, wd, rescale, extra, use_wd):
        g = _common(g, w32, lr, wd, rescale, clip, use_wd)
        if inner is not None:
            new_mom = momentum * inner.astype(jnp.float32) - lr * g
            return w32 + new_mom, new_mom
        return w32 - lr * g, None
    return _FusedUpdate("sgd", apply)


@register_kind("lbsgd")
def _lbsgd(sig):
    """LBSGD: SGD-momentum with a LARS layer-wise lr coefficient.
    The eager path computes the norms on the host (two device syncs per
    key); here they fold into the program — the fused path is where
    LBSGD's host syncs go to die."""
    _, momentum, clip = sig

    def apply(w32, g, inner, lr, wd, rescale, extra, use_wd):
        # eager _get_lars uses the RAW (pre-rescale) gradient
        w_norm = jnp.sqrt(jnp.sum(jnp.square(w32)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        lars = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            w_norm / (g_norm + wd * w_norm + 1e-9) * 0.001, 1.0)
        lr = lr * lars
        g = _common(g, w32, lr, wd, rescale, clip, use_wd)
        if inner is not None:
            new_mom = momentum * inner.astype(jnp.float32) - lr * g
            return w32 + new_mom, new_mom
        return w32 - lr * g, None
    return _FusedUpdate("lbsgd", apply)


@register_kind("adam")
def _adam(sig):
    # bias correction is folded into lr on the host (Adam._fused_lr),
    # exactly like the eager update — lr stays a pure runtime scalar
    _, beta1, beta2, epsilon, clip = sig

    def apply(w32, g, inner, lr, wd, rescale, extra, use_wd):
        g = _common(g, w32, lr, wd, rescale, clip, use_wd)
        mean, var = inner
        new_mean = beta1 * mean + (1 - beta1) * g
        new_var = beta2 * var + (1 - beta2) * jnp.square(g)
        new_w = w32 - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
        return new_w, (new_mean, new_var)
    return _FusedUpdate("adam", apply)


@register_kind("adagrad")
def _adagrad(sig):
    _, epsilon, clip = sig

    def apply(w32, g, inner, lr, wd, rescale, extra, use_wd):
        # adagrad_update applies wd INSIDE the step term, not on g
        g = _clip(g * rescale, clip)
        new_h = inner + jnp.square(g)
        new_w = w32 - lr * (g / jnp.sqrt(new_h + epsilon) + wd * w32)
        return new_w, new_h
    return _FusedUpdate("adagrad", apply)


@register_kind("rmsprop")
def _rmsprop(sig):
    _, gamma1, epsilon, clip, clip_weights = sig

    def apply(w32, g, inner, lr, wd, rescale, extra, use_wd):
        g = _common(g, w32, lr, wd, rescale, clip, use_wd)
        new_n = (1 - gamma1) * jnp.square(g) + gamma1 * inner
        new_w = w32 - lr * g / jnp.sqrt(new_n + epsilon)
        if clip_weights is not None and clip_weights > 0:
            new_w = jnp.clip(new_w, -clip_weights, clip_weights)
        return new_w, new_n
    return _FusedUpdate("rmsprop", apply)


@register_kind("rmspropalex")
def _rmspropalex(sig):
    _, gamma1, gamma2, epsilon, clip, clip_weights = sig

    def apply(w32, gr, inner, lr, wd, rescale, extra, use_wd):
        gr = _common(gr, w32, lr, wd, rescale, clip, use_wd)
        n, gacc, delta = inner
        new_n = (1 - gamma1) * jnp.square(gr) + gamma1 * n
        new_g = (1 - gamma1) * gr + gamma1 * gacc
        new_delta = (gamma2 * delta - lr * gr
                     / jnp.sqrt(new_n - jnp.square(new_g) + epsilon))
        new_w = w32 + new_delta
        if clip_weights is not None and clip_weights > 0:
            new_w = jnp.clip(new_w, -clip_weights, clip_weights)
        return new_w, (new_n, new_g, new_delta)
    return _FusedUpdate("rmspropalex", apply)


@register_kind("adamax")
def _adamax(sig):
    # lr arrives pre-divided by (1 - beta1^t) (Adamax._fused_lr)
    _, beta1, beta2, clip = sig

    def apply(w32, g, inner, lr, wd, rescale, extra, use_wd):
        # eager Adamax: rescale -> +wd -> clip (wd applied
        # unconditionally; wd == 0 adds an exact zero)
        g = _clip(g * rescale + wd * w32, clip)
        m, u = inner
        new_m = beta1 * m + (1.0 - beta1) * g
        new_u = jnp.maximum(beta2 * u, jnp.abs(g))
        return w32 - lr * new_m / new_u, (new_m, new_u)
    return _FusedUpdate("adamax", apply)


@register_kind("nadam")
def _nadam(sig):
    # extra = (momentum_t, momentum_t_1, m_schedule, m_schedule_next,
    # 1 - beta2^t): the schedule product mutates host state per key per
    # step, so it is computed by Nadam._fused_extra in eager key order
    # (schedule_decay, sig[4], only shapes those host-computed extras)
    _, beta1, beta2, epsilon, _schedule_decay, clip = sig

    def apply(w32, g, inner, lr, wd, rescale, extra, use_wd):
        momentum_t, momentum_t_1 = extra[0], extra[1]
        m_schedule, m_schedule_next, bc2 = extra[2], extra[3], extra[4]
        g = _clip(g * rescale + wd * w32, clip)
        m, v = inner
        new_m = beta1 * m + (1.0 - beta1) * g
        new_v = beta2 * v + (1.0 - beta2) * jnp.square(g)
        grad_prime = g / (1.0 - m_schedule)
        m_prime = new_m / (1.0 - m_schedule_next)
        v_prime = new_v / bc2
        m_bar = (1.0 - momentum_t) * grad_prime + momentum_t_1 * m_prime
        new_w = w32 - lr * m_bar / (jnp.sqrt(v_prime) + epsilon)
        return new_w, (new_m, new_v)
    return _FusedUpdate("nadam", apply, n_extra=5)


@register_kind("lamb")
def _lamb(sig):
    # extra = (1 - beta1^t, 1 - beta2^t) when bias_correction
    _, beta1, beta2, epsilon, bias_correction, clip = sig

    def apply(w32, g, inner, lr, wd, rescale, extra, use_wd):
        g = _clip(g * rescale, clip)
        m, v = inner
        new_m = beta1 * m + (1.0 - beta1) * g
        new_v = beta2 * v + (1.0 - beta2) * jnp.square(g)
        if bias_correction:
            m_hat = new_m / extra[0]
            v_hat = new_v / extra[1]
        else:
            m_hat, v_hat = new_m, new_v
        r = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * w32
        w_norm = jnp.sqrt(jnp.sum(jnp.square(w32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        ratio = jnp.where((w_norm > 0) & (r_norm > 0),
                          w_norm / r_norm, 1.0)
        return w32 - lr * ratio * r, (new_m, new_v)
    return _FusedUpdate("lamb", apply, n_extra=2)


# ----------------------------------------------------------------------
# the shared per-key wrapper (multi-precision aware)
# ----------------------------------------------------------------------
def apply_one(upd, w, g, state, mp, lr, wd, rescale, extra, use_wd):
    """One key's fused update. ``state`` is the natural nested state
    structure (jax-array leaves); ``mp`` is the STATIC multi-precision
    flag (the state is ``(inner, weight32)`` and ``w`` is the
    low-precision model weight). Returns ``(new_w, new_state)`` with
    every leaf cast back to its input dtype, the model weight refreshed
    from the f32 result."""
    g32 = g.astype(jnp.float32)
    if mp:
        inner, w32 = state
    else:
        inner = state
        w32 = w.astype(jnp.float32)
    new_w32, new_inner = upd.apply(w32, g32, inner, lr, wd, rescale,
                                   extra, use_wd)
    old_leaves = _leaf_values(state, [])
    new_state = (new_inner, new_w32) if mp else new_inner
    new_leaves = _leaf_values(new_state, [])
    cast = iter([nl.astype(ol.dtype)
                 for nl, ol in zip(new_leaves, old_leaves)])

    def rebuild(s):
        if s is None:
            return None
        if isinstance(s, tuple):
            return tuple(rebuild(x) for x in s)
        return next(cast)
    return new_w32.astype(w.dtype), rebuild(new_state)


def bulk_apply(sig):
    """The ``Optimizer._fused_update`` protocol body for ``sig``: a
    pure function over aligned per-key sequences. ``runtime_scalars``
    carries the runtime values (``lr``/``wd`` vectors, ``rescale``
    scalar, ``extra`` (n_keys, n_extra) matrix) plus the static per-key
    ``mp`` flags and the static ``use_wd`` short-circuit."""
    upd = build(sig)

    def fused_update(params, grads, states, runtime_scalars):
        rt = runtime_scalars
        lr, wd = rt["lr"], rt["wd"]
        rescale = rt["rescale"]
        extra = rt.get("extra")
        mp = rt.get("mp") or (False,) * len(params)
        use_wd = rt.get("use_wd", True)
        new_ps, new_ss = [], []
        for i, (w, g, st) in enumerate(zip(params, grads, states)):
            e = extra[i] if upd.n_extra else ()
            nw, ns = apply_one(upd, w, g, st, mp[i], lr[i], wd[i],
                               rescale, e, use_wd)
            new_ps.append(nw)
            new_ss.append(ns)
        return tuple(new_ps), tuple(new_ss)
    return fused_update


# ----------------------------------------------------------------------
# dynamic loss scaling (bf16/f16 training)
# ----------------------------------------------------------------------
LOSS_SCALE = _telemetry.REGISTRY.gauge(
    "loss_scale",
    "current dynamic loss scale of the fused fit step (published at "
    "sync boundaries — the live value rides on device)")
OVERFLOW_SKIPS = _telemetry.REGISTRY.counter(
    "loss_scale_overflow_skips",
    "fused fit steps whose update was skipped because a non-finite "
    "gradient was detected on device (the loss scale backs off)",
    vital=True)


def scaler_config():
    """Loss-scaling knobs (docs/CONFIG.md). ``MXNET_LOSS_SCALE``:
    ``dynamic`` (default), ``off``, or a float for a static scale (a
    static scale still skips non-finite steps, it just never adjusts).
    Returns None when scaling is disabled."""
    mode = os.environ.get("MXNET_LOSS_SCALE", "dynamic").strip().lower()
    if mode in ("off", "none", "0", ""):
        return None
    init = float(os.environ.get("MXNET_LOSS_SCALE_INIT", str(2.0 ** 15)))
    interval = int(os.environ.get("MXNET_LOSS_SCALE_GROWTH_INTERVAL",
                                  "2000"))
    if mode == "dynamic":
        return {"dynamic": True, "init": init, "interval": interval}
    return {"dynamic": False, "init": float(mode), "interval": interval}


class DynamicLossScaler:
    """Device-resident loss-scale state for low-precision fused
    training. The live ``(scale, good_steps, skips)`` triple is donated
    through the fit program every step; the host copies are refreshed
    only by :meth:`publish` (sync boundaries: ``Module._fit_sync``,
    checkpoint capture, metric readback), so steady-state steps never
    sync. Growth/backoff factors follow the standard 2x/0.5x schedule;
    a non-dynamic scaler keeps the scale fixed but still skips
    non-finite steps."""

    GROWTH = 2.0
    BACKOFF = 0.5
    MAX_SCALE = 2.0 ** 24

    def __init__(self, init_scale=None, growth_interval=None,
                 dynamic=True):
        cfg = scaler_config() or {"dynamic": True, "init": 2.0 ** 15,
                                  "interval": 2000}
        self.dynamic = bool(dynamic if dynamic is not None
                            else cfg["dynamic"])
        self._scale = float(init_scale if init_scale is not None
                            else cfg["init"])
        self.growth_interval = int(growth_interval
                                   if growth_interval is not None
                                   else cfg["interval"])
        self._good = 0
        self._skips = 0
        self._published_skips = 0
        self._dev = None       # live (scale, good, skips) jax arrays

    @classmethod
    def from_config(cls):
        cfg = scaler_config()
        if cfg is None:
            return None
        return cls(init_scale=cfg["init"],
                   growth_interval=cfg["interval"],
                   dynamic=cfg["dynamic"])

    # -- trace-static identity (part of the fit-program cache key) ----
    def trace_sig(self):
        return ("lscale", self.dynamic, self.growth_interval,
                self.GROWTH, self.BACKOFF, self.MAX_SCALE)

    # -- device state -------------------------------------------------
    def device_state(self):
        if self._dev is None:
            self._dev = (jnp.float32(self._scale),
                         jnp.int32(self._good),
                         jnp.int32(self._skips))
        return self._dev

    def set_device_state(self, triple):
        self._dev = tuple(triple)

    def step_fn(self, finite, state):
        """In-program scale adjustment: pure, shapes fixed. Returns the
        new (scale, good, skips) triple."""
        scale, good, skips = state
        new_skips = skips + jnp.where(finite, 0, 1).astype(skips.dtype)
        if not self.dynamic:
            return scale, good, new_skips
        interval = self.growth_interval
        new_good = jnp.where(finite, good + 1, 0).astype(good.dtype)
        grown = jnp.minimum(scale * self.GROWTH, self.MAX_SCALE)
        grow = new_good >= interval
        new_scale = jnp.where(
            finite, jnp.where(grow, grown, scale),
            jnp.maximum(scale * self.BACKOFF, 1.0))
        new_good = jnp.where(grow, 0, new_good).astype(good.dtype)
        return new_scale, new_good, new_skips

    # -- host-side sync boundaries ------------------------------------
    def publish(self):
        """Refresh host copies from the device triple and push
        telemetry. This is a host sync by design — call it only at
        existing sync boundaries, never per step."""
        if self._dev is not None:
            scale, good, skips = self._dev
            # sync-boundary readback by contract (fit sync / checkpoint
            # capture), never per-step
            self._scale = float(scale)
            self._good = int(good)
            self._skips = int(skips)
        LOSS_SCALE.set(self._scale)
        delta = self._skips - self._published_skips
        if delta > 0:
            OVERFLOW_SKIPS.inc(delta)
        self._published_skips = self._skips
        return self._scale

    @property
    def scale(self):
        return self._scale

    @property
    def skips(self):
        return self._skips

    # -- checkpoint (mx.checkpoint extra["loss_scaler"]) --------------
    def state_dict(self):
        self.publish()
        return {"scale": self._scale, "good": self._good,
                "skips": self._skips, "dynamic": self.dynamic,
                "growth_interval": self.growth_interval}

    def load_state_dict(self, d):
        self._scale = float(d.get("scale", self._scale))
        self._good = int(d.get("good", 0))
        self._skips = int(d.get("skips", 0))
        self.dynamic = bool(d.get("dynamic", self.dynamic))
        self.growth_interval = int(d.get("growth_interval",
                                         self.growth_interval))
        self._published_skips = self._skips
        self._dev = None

    @classmethod
    def from_state(cls, d):
        s = cls()
        s.load_state_dict(d)
        return s
