"""Monitor — tap intermediate outputs of bound executors for debugging.

Reference parity: python/mxnet/monitor.py:33 (Monitor installs a callback
via executor.set_monitor_callback; graph_executor.cc SetMonitorCallback
fires it with each op's output). TPU-native: the executor compiles the
whole graph into one XLA program, so intermediates normally never
materialize. With the default statistic the taps STREAM from inside that
one program: the stat (mean |x|) is computed on-device per tap and only
the scalar crosses to the host via ``jax.debug.callback`` — a monitored
batch costs about one plain step plus the stats (the analog of the
reference engine streaming callbacks from in-flight execution; timed in
tests/test_monitor_stream.py). A custom host-side ``stat_func`` falls
back to the "tapped" mode: a second jitted program returning every
intermediate (~2x step cost on monitored batches).
"""
from __future__ import annotations

import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """Collect statistics of intermediate outputs every ``interval``
    batches (reference monitor.py Monitor).

    Monitored batches run an extra tapped forward program (~2x step
    cost; see Executor.set_monitor_callback) — pick ``interval``
    accordingly; batches the interval gate skips pay nothing.

    Parameters
    ----------
    interval : int
        Sample every ``interval`` calls to ``tic()``.
    stat_func : callable(NDArray) -> NDArray, optional
        Statistic to compute per tapped array; default mean(|x|)
        (the reference's asum/size).
    pattern : str
        Regex on tap names; only matches are collected.
    sort : bool
        Sort the toc() result by name.
    monitor_all : bool
        Also tap op *inputs* (weights, data), not just op outputs.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        self._default_stat = stat_func is None
        if stat_func is None:
            def stat_func(x):
                return x.abs().mean()
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all

        def stat_helper(name, array):
            if not self.activated or not self.re_pattern.match(name):
                return
            if not isinstance(array, NDArray):
                array = NDArray(array)
            self.queue.append((self.step, name, self.stat_func(array)))

        def stream_helper(name, array):
            # stream mode: the statistic was already computed on-device
            # inside the compiled step; the tap IS the stat
            if not self.activated or not self.re_pattern.match(name):
                return
            if not isinstance(array, NDArray):
                array = NDArray(array)
            self.queue.append((self.step, name, array))

        # the executor consults this backref to skip the monitored-program
        # launch on batches the interval gate would drop anyway
        stat_helper._monitor = self
        stream_helper._monitor = self
        self.stat_helper = stat_helper
        self.stream_helper = stream_helper

    def install(self, exe):
        """Attach this monitor to an executor. With the default statistic
        the stat runs on-device inside the one compiled step (stream
        mode); a custom host ``stat_func`` uses the tapped fallback."""
        if self._default_stat:
            from .executor import DEFAULT_STREAM_STAT
            exe.set_monitor_callback(
                self.stream_helper, self.monitor_all, mode="stream",
                stat_fn=DEFAULT_STREAM_STAT)
        else:
            exe.set_monitor_callback(self.stat_helper, self.monitor_all,
                                     mode="tapped")
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this batch if the interval has elapsed."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """End collection; returns [(step, name, stat_str)]."""
        if not self.activated:
            return []
        self.activated = False
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                assert isinstance(v, NDArray)
                if v.shape == (1,) or v.shape == ():
                    s += str(v.asnumpy().reshape(-1)[0]) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        """End collection and print the collected stats."""
        res = self.toc()
        for n, k, v in res:
            print("Batch: {:7d} {:30s} {:s}".format(n, k, v))
