"""Monitor — sample statistics of intermediate tensors in bound executors.

Behavioral parity: python/mxnet/monitor.py:33 (install via
``executor.set_monitor_callback``; ``tic()``/``toc_print()`` around a batch).
TPU-native design: the executor compiles the whole graph into ONE XLA
program, so intermediates normally never materialise.  With the default
statistic the taps STREAM from inside that program — the stat (mean |x|) is
computed on-device per tap and only the scalar crosses to the host via
``jax.debug.callback``; a monitored batch costs about one plain step plus
the stats (timed bound in tests/test_monitor_stream.py).  A custom
host-side ``stat_func`` falls back to "tapped" mode: a second jitted
program returning every intermediate (~2x step cost on monitored batches).
"""
from __future__ import annotations

import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


def _render_stat(value):
    """Format one collected stat value (NDArray or list of them) the way the
    reference prints: scalars bare, tensors via numpy repr, tab-joined."""
    values = value if isinstance(value, list) else [value]
    parts = []
    for v in values:
        if not isinstance(v, NDArray):
            raise TypeError(f"monitor stat must be NDArray, got {type(v)}")
        arr = v.asnumpy()
        parts.append(str(arr.reshape(-1)[0]) if arr.size == 1 else str(arr))
    return "\t".join(parts) + "\t"


class Monitor:
    """Collect per-tensor statistics every ``interval`` batches.

    Parameters mirror the reference: ``interval`` (sampling period in
    ``tic()`` calls), ``stat_func`` (host statistic; None selects the
    on-device streaming default of mean(|x|)), ``pattern`` (regex filter on
    tap names), ``sort`` (order ``toc()`` output by name), ``monitor_all``
    (also tap op inputs — weights and data — not just outputs).
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        self.interval = interval
        self.stat_func = stat_func
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        # Executors consult this backref on the callback to skip launching
        # the monitored program on batches the interval gate drops.
        self._tap = self._make_tap(device_stat=stat_func is None)
        self._tap._monitor = self

    def _make_tap(self, device_stat):
        """Build the (name, array) callback handed to executors.  In stream
        mode the array already IS the on-device statistic; in tapped mode we
        apply the host stat_func here."""
        def tap(name, array):
            if not self.activated or not self.re_pattern.match(name):
                return
            if not isinstance(array, NDArray):
                array = NDArray(array)
            stat = array if device_stat else self.stat_func(array)
            self.queue.append((self.step, name, stat))
        return tap

    def install(self, exe):
        """Attach to an executor.  Default statistic → stream mode (stat
        computed inside the compiled step); custom ``stat_func`` → tapped
        fallback."""
        if self.stat_func is None:
            from .executor import DEFAULT_STREAM_STAT
            exe.set_monitor_callback(self._tap, self.monitor_all,
                                     mode="stream",
                                     stat_fn=DEFAULT_STREAM_STAT)
        else:
            exe.set_monitor_callback(self._tap, self.monitor_all,
                                     mode="tapped")
        self.exes.append(exe)

    # Back-compat aliases for the reference's two callback attributes
    # (settable: tests wrap the callback to observe taps).
    @property
    def stat_helper(self):
        return self._tap

    @stat_helper.setter
    def stat_helper(self, fn):
        if not hasattr(fn, "_monitor"):
            fn._monitor = self
        self._tap = fn

    @property
    def stream_helper(self):
        return self._tap

    @stream_helper.setter
    def stream_helper(self, fn):
        self.stat_helper = fn

    def tic(self):
        """Arm collection for this batch when the interval has elapsed."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Disarm and drain: returns [(step, name, stat_str)]."""
        if not self.activated:
            return []
        self.activated = False
        drained = sorted(self.queue, key=lambda rec: rec[1]) if self.sort \
            else self.queue
        self.queue = []
        return [(step, name, _render_stat(val)) for step, name, val in drained]

    def toc_print(self):
        """Disarm, drain, and print one line per collected stat."""
        for step, name, text in self.toc():
            print(f"Batch: {step:7d} {name:30s} {text}")
