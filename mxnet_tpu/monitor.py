"""Monitor — tap intermediate outputs of bound executors for debugging.

Reference parity: python/mxnet/monitor.py:33 (Monitor installs a callback
via executor.set_monitor_callback; graph_executor.cc SetMonitorCallback
fires it with each op's output). TPU-native: the executor compiles the
whole graph into one XLA program, so intermediates normally never
materialize; when a monitor callback is installed the executor runs a
separate jitted "tapped" program that also returns every node output
(executor.py _build_monitor_fn) and fires the callback per tap. This is a
debug path — it costs one extra program launch per monitored forward.
"""
from __future__ import annotations

import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """Collect statistics of intermediate outputs every ``interval``
    batches (reference monitor.py Monitor).

    Monitored batches run an extra tapped forward program (~2x step
    cost; see Executor.set_monitor_callback) — pick ``interval``
    accordingly; batches the interval gate skips pay nothing.

    Parameters
    ----------
    interval : int
        Sample every ``interval`` calls to ``tic()``.
    stat_func : callable(NDArray) -> NDArray, optional
        Statistic to compute per tapped array; default mean(|x|)
        (the reference's asum/size).
    pattern : str
        Regex on tap names; only matches are collected.
    sort : bool
        Sort the toc() result by name.
    monitor_all : bool
        Also tap op *inputs* (weights, data), not just op outputs.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        if stat_func is None:
            def stat_func(x):
                return x.abs().mean()
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all

        def stat_helper(name, array):
            if not self.activated or not self.re_pattern.match(name):
                return
            if not isinstance(array, NDArray):
                array = NDArray(array)
            self.queue.append((self.step, name, self.stat_func(array)))

        # the executor consults this backref to skip the tapped-program
        # launch on batches the interval gate would drop anyway
        stat_helper._monitor = self
        self.stat_helper = stat_helper

    def install(self, exe):
        """Attach this monitor to an executor."""
        exe.set_monitor_callback(self.stat_helper, self.monitor_all)
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this batch if the interval has elapsed."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """End collection; returns [(step, name, stat_str)]."""
        if not self.activated:
            return []
        self.activated = False
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                assert isinstance(v, NDArray)
                if v.shape == (1,) or v.shape == ():
                    s += str(v.asnumpy().reshape(-1)[0]) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        """End collection and print the collected stats."""
        res = self.toc()
        for n, k, v in res:
            print("Batch: {:7d} {:30s} {:s}".format(n, k, v))
