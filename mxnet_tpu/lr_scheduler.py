"""Learning-rate schedules (behavioral parity: python/mxnet/lr_scheduler.py
— same classes, same curves; ``WarmupScheduler`` and ``CosineScheduler``
match the rahul003 fork's additions)."""
from __future__ import annotations

import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler", "WarmupScheduler"]


class LRScheduler:
    """Maps ``num_update`` (the optimizer's update counter) to a learning
    rate.  Stateful: the rate never rewinds if ``num_update`` goes
    backwards (matters under async/parameter-server replay)."""

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update):
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """Geometric decay: multiply by ``factor`` once per ``step`` updates,
    floored at ``stop_factor_lr``."""

    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8, base_lr=0.01):
        super().__init__(base_lr)
        if step < 1:
            raise ValueError("step must be >= 1")
        if factor > 1.0:
            raise ValueError("factor must be <= 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self._decays_applied = 0

    def __call__(self, num_update):
        # decays owed so far: one per whole `step` strictly before num_update
        due = max(0, num_update - 1) // self.step
        while self._decays_applied < due:
            self._decays_applied += 1
            self.base_lr = max(self.base_lr * self.factor,
                               self.stop_factor_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """Multiply by ``factor`` as ``num_update`` passes each boundary in the
    increasing list ``step``."""

    def __init__(self, step, factor=1.0, base_lr=0.01):
        super().__init__(base_lr)
        if any(a >= b for a, b in zip(step, step[1:])):
            raise ValueError("steps must be increasing")
        self.step = list(step)
        self.factor = factor
        self._next_boundary = 0

    def __call__(self, num_update):
        while (self._next_boundary < len(self.step)
               and num_update > self.step[self._next_boundary]):
            self._next_boundary += 1
            self.base_lr *= self.factor
        return self.base_lr


class _AnnealingScheduler(LRScheduler):
    """Shared shape-based annealing from base_lr to final_lr over
    ``max_update`` steps; subclasses supply the unit-interval shape."""

    def __init__(self, max_update, base_lr, final_lr):
        super().__init__(base_lr)
        self.base_lr_orig = base_lr
        self.max_update = max_update
        self.final_lr = final_lr

    def _shape(self, t):
        """Remaining-lr fraction at progress t in [0, 1]."""
        raise NotImplementedError

    def __call__(self, num_update):
        if num_update <= self.max_update:
            span = self.base_lr_orig - self.final_lr
            self.base_lr = self.final_lr + \
                span * self._shape(num_update / self.max_update)
        return self.base_lr


class PolyScheduler(_AnnealingScheduler):
    """Polynomial decay: lr follows (1 - t)^pwr down to ``final_lr``."""

    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0):
        super().__init__(max_update, base_lr, final_lr)
        self.power = pwr

    def _shape(self, t):
        return (1 - t) ** self.power


class CosineScheduler(_AnnealingScheduler):
    """Half-cosine decay from base_lr to ``final_lr``."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0):
        super().__init__(max_update, base_lr, final_lr)

    def _shape(self, t):
        return (1 + math.cos(math.pi * t)) / 2


class WarmupScheduler(LRScheduler):
    """Linear ramp from ``warmup_begin_lr`` to the wrapped scheduler's base
    rate over ``warmup_steps``, then defer to the wrapped scheduler."""

    def __init__(self, scheduler, warmup_steps, warmup_begin_lr=0.0):
        super().__init__(scheduler.base_lr)
        self.scheduler = scheduler
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr

    def __call__(self, num_update):
        if num_update >= self.warmup_steps:
            return self.scheduler(num_update)
        ramp = num_update / self.warmup_steps
        return self.warmup_begin_lr + \
            ramp * (self.scheduler.base_lr - self.warmup_begin_lr)
