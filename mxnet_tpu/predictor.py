"""Predictor — the standalone inference entry.

Reference parity: src/c_api/c_predict_api.cc (MXPredCreate /
MXPredSetInput / MXPredForward / MXPredGetOutput — the deployment API
the amalgamation build ships). TPU-native: one class that loads
``prefix-symbol.json`` + ``prefix-%04d.params`` (or the raw
json/params bytes, like the C API takes buffers), binds an
inference-only executor, and runs jitted forwards. Reshape re-binds
with the jit cache keyed on shape, mirroring MXPredReshape.

Usage::

    pred = mx.predictor.Predictor.load("model", epoch=9,
                                       input_shapes={"data": (1, 3, 224, 224)})
    out = pred.forward(data=batch)[0]        # numpy in, numpy out

A Predictor is single-threaded like the reference's PredictorHandle
(forward mutates bound input state); for concurrent traffic use
``mx.serving.ModelServer``, which gives each replica its own Predictor
behind a thread-safe queue.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import current_context

__all__ = ["Predictor"]


class Predictor:
    """Inference-only bound model (see module docstring)."""

    def __init__(self, symbol, arg_params, aux_params, input_shapes,
                 ctx=None, dtype="float32"):
        from .ndarray.ndarray import NDArray
        self._ctx = ctx if ctx is not None else current_context()
        self._symbol = symbol
        self._dtype = dtype
        self._input_names = list(input_shapes)
        self._input_shapes = {n: tuple(s) for n, s in input_shapes.items()}
        type_dict = {n: dtype for n in input_shapes} \
            if dtype != "float32" else None
        self._exe = symbol.simple_bind(ctx=self._ctx, grad_req="null",
                                       type_dict=type_dict, **input_shapes)
        missing = [n for n in self._exe.arg_dict
                   if n not in arg_params and n not in input_shapes]
        # training-only label inputs are ignored by eval forward; leave
        # them zero (the reference deploys the same symbol by slicing off
        # the loss, but SoftmaxOutput's forward is label-free anyway)
        real_missing = [n for n in missing if not n.endswith("label")]
        real_missing += [n for n in self._exe.aux_dict
                         if n not in (aux_params or {})]
        if real_missing:
            raise MXNetError("params missing for %s" % real_missing)
        self._exe.copy_params_from(
            {k: v if isinstance(v, NDArray) else NDArray(_np.asarray(v))
             for k, v in arg_params.items()},
            {k: v if isinstance(v, NDArray) else NDArray(_np.asarray(v))
             for k, v in (aux_params or {}).items()},
            allow_extra_params=True)
        self._arg_params = arg_params
        self._aux_params = aux_params

    # ------------------------------------------------------------------
    @staticmethod
    def load(prefix, epoch, input_shapes, ctx=None, dtype="float32"):
        """Load ``prefix-symbol.json`` + ``prefix-%04d.params``
        (MXPredCreate's file form)."""
        from . import model as _model
        sym, arg_params, aux_params = _model.load_checkpoint(prefix, epoch)
        return Predictor(sym, arg_params, aux_params, input_shapes, ctx,
                         dtype)

    @staticmethod
    def create(symbol_json, param_bytes, input_shapes, ctx=None,
               dtype="float32"):
        """Create from in-memory buffers (MXPredCreate's buffer form:
        the json string and the serialized params blob)."""
        from . import symbol as _sym
        from .serialization import load_ndarray_bytes
        sym = _sym.load_json(symbol_json)
        saved = load_ndarray_bytes(param_bytes)
        arg_params, aux_params = {}, {}
        for k, v in saved.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        return Predictor(sym, arg_params, aux_params, input_shapes, ctx,
                         dtype)

    # ------------------------------------------------------------------
    def forward(self, **inputs):
        """Set inputs, run forward, return a list of host numpy outputs
        (MXPredSetInput + MXPredForward + MXPredGetOutput in one call).

        Inputs may be numpy arrays, ``NDArray``, raw ``jax.Array``
        (device-resident values stay zero-copy on device), or anything
        ``np.asarray`` accepts. Shapes are validated against the bind
        shapes up front (MXPredSetInput's size check), so a mismatched
        feed fails with a clear error instead of a trace-time one."""
        import jax
        from .ndarray.ndarray import NDArray
        norm = {}
        for name, v in inputs.items():
            # declared inputs only (MXPredSetInput's contract) — checking
            # the full arg_dict would let a typo'd name silently overwrite
            # bound WEIGHTS and corrupt every later forward
            if name not in self._input_shapes:
                raise MXNetError("unknown input %r (bound inputs: %s)"
                                 % (name, self._input_names))
            dst = self._exe.arg_dict[name]
            if isinstance(v, jax.Array):
                v = NDArray(v)
            elif not isinstance(v, NDArray):
                v = _np.asarray(v)
            if tuple(v.shape) != dst.shape:
                raise MXNetError(
                    "input %r: shape %s does not match bind shape %s "
                    "(use reshape() to re-bind)"
                    % (name, tuple(v.shape), dst.shape))
            norm[name] = v
        self._exe.forward(is_train=False, **norm)
        return [o.asnumpy() for o in self._exe.outputs]

    def reshape(self, input_shapes):
        """Re-bind for new input shapes (MXPredReshape). The returned
        Predictor SHARES this one's device-resident parameters through
        ``Executor.reshape`` — no host->device weight copy — and the jit
        cache is per symbol, so flipping between shapes (e.g. serving's
        batch-size buckets) never recompiles an already-seen shape."""
        unknown = [n for n in input_shapes if n not in self._exe.arg_dict]
        if unknown:
            raise MXNetError("reshape: unknown input(s) %s (bound inputs: %s)"
                             % (unknown, self._input_names))
        merged = dict(self._input_shapes)
        merged.update({n: tuple(s) for n, s in input_shapes.items()})
        new = Predictor.__new__(Predictor)
        new._ctx = self._ctx
        new._symbol = self._symbol
        new._dtype = self._dtype
        new._input_names = list(merged)
        new._input_shapes = merged
        new._exe = self._exe.reshape(partial_shaping=True, **merged)
        new._arg_params = self._arg_params
        new._aux_params = self._aux_params
        return new

    @property
    def input_shapes(self):
        """Bind-time input shapes ({name: shape tuple})."""
        return dict(self._input_shapes)

    @property
    def output_names(self):
        return self._symbol.list_outputs()


# ----------------------------------------------------------------------
# helpers for the embedded C predict API (src/c_predict_api.cc) — the
# C side passes flat float32 buffers; these reshape to the bind shapes,
# run forward, and hand back C-contiguous float32 numpy arrays
# ----------------------------------------------------------------------
def _c_api_forward(pred, flat_inputs):
    """Run ``pred`` on a dict of FLAT float32 numpy arrays, reshaping
    each to its bind-time shape. Returns a list of float32 C-contiguous
    outputs (filtered to ``_c_api_partial_outputs`` when set)."""
    inputs = {}
    for name, flat in flat_inputs.items():
        shape = pred._exe.arg_dict[name].shape
        inputs[name] = _np.ascontiguousarray(
            _np.asarray(flat, _np.float32).reshape(shape))
    outs = pred.forward(**inputs)
    wanted = getattr(pred, "_c_api_partial_outputs", None)
    if wanted:
        names = pred.output_names
        index = {n: i for i, n in enumerate(names)}
        picked = []
        for key in wanted:
            if key in index:
                picked.append(outs[index[key]])
            elif key + "_output" in index:
                picked.append(outs[index[key + "_output"]])
            else:
                raise MXNetError("unknown output %r (have %s)"
                                 % (key, names))
        outs = picked
    return [_np.ascontiguousarray(_np.asarray(o, _np.float32))
            for o in outs]


def _c_api_ndlist(blob):
    """Decode a serialized NDArray dict blob into ([keys], [float32
    arrays]) for MXNDListCreate."""
    from .serialization import load_ndarray_bytes
    saved = load_ndarray_bytes(bytes(blob))
    keys, arrays = [], []
    for k, v in saved.items():
        keys.append(k)
        arrays.append(_np.ascontiguousarray(
            _np.asarray(v.asnumpy(), _np.float32)))
    return keys, arrays


def _c_api_set_partial_outputs(pred, keys):
    """Validate + install a partial-output selection (fails fast at
    MXPredCreatePartialOut time, like the reference)."""
    names = pred.output_names
    for key in keys:
        if key not in names and key + "_output" not in names:
            raise MXNetError("unknown output %r (have %s)" % (key, names))
    pred._c_api_partial_outputs = list(keys)
    return True


def _c_api_output_shapes(pred):
    """Bind-time output shapes (list of tuples), honoring a partial-out
    selection — the reference serves shapes right after MXPredCreate."""
    shapes = {n: pred._exe.arg_dict[n].shape for n in pred._input_names}
    out_shapes = pred._symbol.infer_shape(**shapes)[1]
    names = pred.output_names
    wanted = getattr(pred, "_c_api_partial_outputs", None)
    if wanted:
        index = {n: i for i, n in enumerate(names)}
        picked = []
        for key in wanted:
            i = index.get(key, index.get(key + "_output"))
            picked.append(out_shapes[i])
        out_shapes = picked
    return [tuple(int(d) for d in s) for s in out_shapes]


def _c_api_input_size(pred, name):
    """Element count of a bind-time input, or -1 if unknown."""
    arr = pred._exe.arg_dict.get(name)
    if arr is None:
        return -1
    n = 1
    for d in arr.shape:
        n *= int(d)
    return n
