"""Predictor — the standalone inference entry.

Reference parity: src/c_api/c_predict_api.cc (MXPredCreate /
MXPredSetInput / MXPredForward / MXPredGetOutput — the deployment API
the amalgamation build ships). TPU-native: one class that loads
``prefix-symbol.json`` + ``prefix-%04d.params`` (or the raw
json/params bytes, like the C API takes buffers), binds an
inference-only executor, and runs jitted forwards. Reshape re-binds
with the jit cache keyed on shape, mirroring MXPredReshape.

Usage::

    pred = mx.predictor.Predictor.load("model", epoch=9,
                                       input_shapes={"data": (1, 3, 224, 224)})
    out = pred.forward(data=batch)[0]        # numpy in, numpy out
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import current_context

__all__ = ["Predictor"]


class Predictor:
    """Inference-only bound model (see module docstring)."""

    def __init__(self, symbol, arg_params, aux_params, input_shapes,
                 ctx=None, dtype="float32"):
        from .ndarray.ndarray import NDArray
        self._ctx = ctx if ctx is not None else current_context()
        self._symbol = symbol
        self._dtype = dtype
        self._input_names = list(input_shapes)
        type_dict = {n: dtype for n in input_shapes} \
            if dtype != "float32" else None
        self._exe = symbol.simple_bind(ctx=self._ctx, grad_req="null",
                                       type_dict=type_dict, **input_shapes)
        missing = [n for n in self._exe.arg_dict
                   if n not in arg_params and n not in input_shapes]
        # training-only label inputs are ignored by eval forward; leave
        # them zero (the reference deploys the same symbol by slicing off
        # the loss, but SoftmaxOutput's forward is label-free anyway)
        real_missing = [n for n in missing if not n.endswith("label")]
        real_missing += [n for n in self._exe.aux_dict
                         if n not in (aux_params or {})]
        if real_missing:
            raise MXNetError("params missing for %s" % real_missing)
        self._exe.copy_params_from(
            {k: v if isinstance(v, NDArray) else NDArray(_np.asarray(v))
             for k, v in arg_params.items()},
            {k: v if isinstance(v, NDArray) else NDArray(_np.asarray(v))
             for k, v in (aux_params or {}).items()},
            allow_extra_params=True)
        self._arg_params = arg_params
        self._aux_params = aux_params

    # ------------------------------------------------------------------
    @staticmethod
    def load(prefix, epoch, input_shapes, ctx=None, dtype="float32"):
        """Load ``prefix-symbol.json`` + ``prefix-%04d.params``
        (MXPredCreate's file form)."""
        from . import model as _model
        sym, arg_params, aux_params = _model.load_checkpoint(prefix, epoch)
        return Predictor(sym, arg_params, aux_params, input_shapes, ctx,
                         dtype)

    @staticmethod
    def create(symbol_json, param_bytes, input_shapes, ctx=None,
               dtype="float32"):
        """Create from in-memory buffers (MXPredCreate's buffer form:
        the json string and the serialized params blob)."""
        from . import symbol as _sym
        from .serialization import load_ndarray_bytes
        sym = _sym.load_json(symbol_json)
        saved = load_ndarray_bytes(param_bytes)
        arg_params, aux_params = {}, {}
        for k, v in saved.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        return Predictor(sym, arg_params, aux_params, input_shapes, ctx,
                         dtype)

    # ------------------------------------------------------------------
    def forward(self, **inputs):
        """Set inputs (numpy or NDArray), run forward, return a list of
        host numpy outputs (MXPredSetInput + MXPredForward +
        MXPredGetOutput in one call)."""
        self._exe.forward(is_train=False, **inputs)
        return [o.asnumpy() for o in self._exe.outputs]

    def reshape(self, input_shapes):
        """Re-bind for new input shapes, keeping params and dtype
        (MXPredReshape)."""
        return Predictor(self._symbol, self._arg_params, self._aux_params,
                         input_shapes, self._ctx, self._dtype)

    @property
    def output_names(self):
        return self._symbol.list_outputs()
