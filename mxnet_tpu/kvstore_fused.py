"""Compiled bucketed kvstore hot path (docs/KVSTORE.md).

The eager ``KVStore.push`` is a per-key Python loop: one compression
round-trip, one add-chain, and one updater dispatch per parameter. MXNet's
CommDevice got its speed from bucketed big-array reduction; this module
reproduces that shape, compiled: same-dtype gradients are packed into
size-capped buckets (``MXNET_KVSTORE_BIGARRAY_BOUND`` bytes, the analog
of MXNet's big-array bound) and each bucket runs ONE jitted computation
per step:

    2-bit quantize (error-feedback residual, donated)
      -> dequantize -> cross-device reduce
      -> fused optimizer apply (or plain assign when no updater is set)

Step functions are cached by (keyset, shapes, dtype, compression config,
optimizer signature) so steady-state training hits the compile cache with
zero retraces — ``TRACE_COUNT`` increments only when a bucket program is
(re)traced, and tests pin that it stays flat after the first step.

Priorities finally do something: pushes carry ``priority=`` into the
pending queue, buckets are formed and dispatched in descending priority,
and XLA's async dispatch overlaps the bucket computations with whatever
host work (remaining backward) follows the push. ``pull``/``barrier``/
state save are the sync points that flush pending work.

The optimizer apply is built from the SHARED fused-update builder
(fused_update.py): any optimizer describing its update via
``Optimizer._fused_sig`` — SGD, Adam, LAMB, RMSProp, ... including
multi-precision ``(inner, weight32)`` state tuples and f16/bf16
weights with f32 masters — runs inside the bucket program. 2-bit
error-feedback residuals always live in f32 (the master-gradient
view), so compression semantics are dtype-independent.

Fallbacks stay eager per-key (and correct): row_sparse values,
custom updaters, and optimizers without a fused signature (slug
``unfused_optimizer:<Name>`` on the kvstore_fallbacks counter).
"""
from __future__ import annotations

import os

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from .ndarray import NDArray
from . import profiler
from . import telemetry as _telemetry
from . import fused_update as _fused

__all__ = ["FusedBucketEngine", "bucket_byte_cap", "TRACE_COUNT",
           "two_bit_quantize", "fused_sgd_apply", "overlap_enabled",
           "OVERLAP_DISPATCHES", "OVERLAP_WINDOW_MS"]


def two_bit_quantize(residual, grad, threshold):
    """Error-feedback 2-bit quantize for one device stream: returns
    ``(q, new_residual)``. The op sequence (add, exact-constant selects,
    subtract) matches TwoBitCompressor.compress_decompress bit-for-bit;
    it is SHARED by the bucketed kvstore step and the fused fit step
    (module/fused_fit.py) so cross-path parity is structural, not
    maintained by hand in two places.  ``MXNET_Q2BIT_IMPL`` selects the
    fused Pallas kernel (pallas/quant.py — same op sequence, so still
    bit-exact) instead of this elementwise XLA chain."""
    from .pallas import two_bit_quantize_fused, use_q2bit_pallas
    if use_q2bit_pallas():
        return two_bit_quantize_fused(residual, grad, threshold)
    t = jnp.asarray(threshold, dtype=grad.dtype)
    acc = residual + grad
    q = jnp.where(acc > t, t, jnp.where(acc < -t, -t, jnp.zeros_like(acc)))
    return q, acc - q


def fused_sgd_apply(w, g_reduced, state, lr, wd, rescale, momentum, clip,
                    use_wd):
    """One key's SGD(-momentum) apply, identical op sequence to
    ops/optimizer_ops.py sgd(_mom)_update (rescale -> clip -> wd ->
    momentum); shared by the bucket program and the fused fit step.
    ``state`` None means plain SGD. Returns (new_w, new_state|None)."""
    g = g_reduced.astype(jnp.float32) * rescale
    if clip is not None and clip >= 0:
        g = jnp.clip(g, -clip, clip)
    if use_wd:
        g = g + wd * w.astype(jnp.float32)
    if state is not None:
        new_mom = momentum * state.astype(jnp.float32) - lr * g
        new_w = w.astype(jnp.float32) + new_mom
        return new_w.astype(w.dtype), new_mom.astype(state.dtype)
    new_w = w.astype(jnp.float32) - lr * g
    return new_w.astype(w.dtype), None

# incremented inside each bucket step function at trace time only; a
# steady-state step that hits the jit cache leaves it untouched. The
# count lives in the mx.telemetry registry (kvstore_bucket_retraces);
# the module-level ``TRACE_COUNT`` name stays a live alias via
# __getattr__ below, so existing zero-retrace pins keep working.
BUCKET_RETRACES = _telemetry.REGISTRY.counter(
    "kvstore_bucket_retraces",
    "compiled bucket-program (re)traces (the TRACE_COUNT witness)",
    vital=True)
DISPATCH_MS = _telemetry.REGISTRY.histogram(
    "kvstore_dispatch_ms",
    "host wall time to dispatch one bucket program (async enqueue)",
    unit="ms")
# backward-overlap witness (docs/KVSTORE.md "Overlapped push"): a bucket
# dispatched by the STREAMING flush leaves the host before the final
# backward bucket's grads have even been enqueued — comms provably
# overlap the remaining backward walk. Ticked only there (never by the
# end-of-push flush), so a positive delta IS the overlap proof the
# bench/tests gate on.
OVERLAP_DISPATCHES = _telemetry.REGISTRY.counter(
    "kvstore_overlap_dispatches",
    "bucket programs dispatched by the streaming flush BEFORE the final "
    "backward bucket landed (the comm/compute overlap witness)",
    vital=True)
OVERLAP_WINDOW_MS = _telemetry.REGISTRY.histogram(
    "kvstore_overlap_window_ms",
    "host wall time from the first overlapped bucket dispatch of a push "
    "walk to the walk's final flush (the window comms had to hide in "
    "backward)", unit="ms")


def overlap_enabled():
    """Backward-overlapped bucket dispatch (``MXNET_KVSTORE_OVERLAP``,
    default on). 0 restores the serial shape: streaming-flushed buckets
    still dispatch in availability order, but the cross-host wire (tpu
    host transport) runs inline and the overlap witness stays silent."""
    return os.environ.get("MXNET_KVSTORE_OVERLAP", "1") != "0"
# shared RetraceSite semantics with executor / fused_fit: step bodies
# call _note_retrace() at trace time; _dispatch times through it.
# _dispatch wraps a non-jitted inner, so bucket programs register with
# the compiled-program registry at their cache-miss sites below
_SITE = _telemetry.RetraceSite(BUCKET_RETRACES, _telemetry.JIT_COMPILE_MS,
                               site="kvstore_bucket")
_note_retrace = _SITE.note


def __getattr__(name):
    if name == "TRACE_COUNT":
        return int(BUCKET_RETRACES.value)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))


_DEFAULT_BUCKET_BYTES = 4 << 20


def bucket_byte_cap():
    """Flat-bucket size cap in bytes (env ``MXNET_KVSTORE_BIGARRAY_BOUND``,
    default 4 MiB). A single value larger than the cap gets its own
    bucket, like the reference's big-array bypass."""
    return int(os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND",
                              _DEFAULT_BUCKET_BYTES))


# kvstore profiler counters (thread-safe Counter; emitted into the chrome
# trace whenever the profiler is running, readable as .value always)
_domain = profiler.Domain("kvstore")
BYTES_PUSHED = _domain.new_counter("kvstore_bytes_pushed", vital=True)
COMPRESS_RATIO = _domain.new_counter("kvstore_compress_ratio", vital=True)
BUCKET_COUNT = _domain.new_counter("kvstore_bucket_count", vital=True)


def _single_device(x):
    """The one device an array is committed/placed on, or None when the
    array is mesh-sharded (left where it is — XLA handles it SPMD)."""
    try:
        ds = x.devices()
    except AttributeError:
        return None
    return next(iter(ds)) if len(ds) == 1 else None


def _on_device(x, dev):
    if dev is None or _single_device(x) in (dev, None):
        return x
    return jax.device_put(x, dev)


def _build_step(layout, n_dev, threshold, mode, tpls, mp_flags, use_wd,
                sentinel=False):
    """Compile-once bucket program: the whole bucket — 2-bit compress with
    error feedback, cross-device reduce, and the optimizer apply for every
    key — is ONE jitted computation.

    Without compression the per-key arrays are NOT physically
    concatenated: XLA fuses each key's reduce+update chain into one kernel
    either way, and a real flatten would read+write every gradient byte an
    extra time purely to rearrange memory (measured 0.8x vs eager on CPU;
    per-key-in-one-program wins).

    With compression the bucket IS physically flat: each device's
    gradients concatenate into one flat f32 buffer (the master-gradient
    view — low-precision gradients are cast first, so residual semantics
    are dtype-independent), quantize against a single DONATED flat
    error-feedback residual per device, reduce flat, and only the
    optimizer apply slices back per key. That turns n_keys × n_dev
    tiny quantize kernels — plus as many residual output buffers and
    host-side writebacks — into n_dev of each.

    layout: tuple of (offset, size, shape) per key — the flat layout.
    mode: None for plain assign (no updater), or the optimizer's fused
    signature, e.g. ("sgd", momentum, clip) — built into the per-key
    apply via the SHARED fused-update builder (fused_update.py).
    rescale_grad / lr / wd / per-key extra scalars are runtime
    arguments, not compile keys, so per-batch rewrites (gluon
    Trainer.step) and schedule steps never retrace.
    tpls: per-key state template (fused_update.state_template) — states
    cross the jit boundary as flat leaf tuples and are rebuilt inside.
    mp_flags: per-key static multi-precision flag — True where the state
    is an ``(inner, weight32)`` master-weight tuple.
    """
    n_keys = len(layout)

    def _reduce(residuals, grads):
        """Compress (error feedback) then sum over devices; returns
        (per-key reduced list, new flat residuals). The op sequence
        mirrors TwoBitCompressor.compress_decompress and
        KVStore._local_reduce exactly (elementwise quantize, sequential
        adds in device order) so results are bit-identical to the eager
        path."""
        if threshold is None:
            reduced = []
            for i in range(n_keys):
                acc = grads[0][i]
                for d in range(1, n_dev):
                    acc = acc + grads[d][i]
                reduced.append(acc)
            return reduced, ()
        dev_q, new_res = [], []
        for d in range(n_dev):
            parts = [grads[d][i].reshape(-1).astype(jnp.float32)
                     for i in range(n_keys)]
            g = parts[0] if n_keys == 1 else jnp.concatenate(parts)
            q, r = two_bit_quantize(residuals[d], g, threshold)
            new_res.append(r)
            dev_q.append(q)
        flat = dev_q[0]
        for q in dev_q[1:]:
            flat = flat + q
        reduced = [lax.slice(flat, (off,), (off + size,)).reshape(shape)
                   for off, size, shape in layout]
        return reduced, tuple(new_res)

    def _nonfinite(grads):
        """Per-bucket isfinite witness (docs/OBSERVABILITY.md): count of
        non-finite gradient elements across every device stream, folded
        into the SAME bucket program as a single scalar — no extra
        dispatch, read only at sync boundaries via a donated
        accumulator."""
        nf = jnp.float32(0.0)
        for d in range(n_dev):
            for i in range(n_keys):
                nf = nf + jnp.sum(
                    (~jnp.isfinite(grads[d][i])).astype(jnp.float32))
        return nf

    from .aot.store import safe_donate_argnums as _donate

    if mode is None:
        if sentinel:
            def step(residuals, grads, nf_acc):
                _note_retrace()
                reduced, new_res = _reduce(residuals, grads)
                return tuple(reduced), new_res, nf_acc + _nonfinite(grads)
            return jax.jit(step, donate_argnums=_donate((0, 2)))

        def step(residuals, grads):
            _note_retrace()
            reduced, new_res = _reduce(residuals, grads)
            return tuple(reduced), new_res
        return jax.jit(step, donate_argnums=_donate((0,)))

    upd = _fused.build(mode)

    def _apply(weights, states, residuals, grads, lr_vec, wd_vec,
               rescale, extra):
        reduced, new_res = _reduce(residuals, grads)
        new_ws, new_ss = [], []
        for i in range(n_keys):
            st = _fused.unflatten(tpls[i], states[i])
            e = extra[i] if upd.n_extra else ()
            new_w, new_s = _fused.apply_one(
                upd, weights[i], reduced[i], st, mp_flags[i],
                lr_vec[i], wd_vec[i], rescale, e, use_wd)
            new_ws.append(new_w)
            new_ss.append(tuple(_fused.flatten_state(new_s)[0]))
        return tuple(new_ws), tuple(new_ss), new_res

    if sentinel:
        def step(weights, states, residuals, grads, lr_vec, wd_vec,
                 rescale, extra, nf_acc):
            _note_retrace()
            new_ws, new_ss, new_res = _apply(
                weights, states, residuals, grads, lr_vec, wd_vec,
                rescale, extra)
            return new_ws, new_ss, new_res, nf_acc + _nonfinite(grads)
        return jax.jit(step, donate_argnums=_donate((1, 2, 8)))

    def step(weights, states, residuals, grads, lr_vec, wd_vec, rescale,
             extra):
        _note_retrace()
        return _apply(weights, states, residuals, grads, lr_vec, wd_vec,
                      rescale, extra)
    return jax.jit(step, donate_argnums=_donate((1, 2)))


class _Pending:
    # grad buffers are SNAPSHOTTED at push time (MXNet's push-at-call
    # semantics): a later in-place write to the pushed NDArray rebinds
    # its ._data and must not change what an async flush applies
    __slots__ = ("key", "data", "likes", "priority", "seq", "size",
                 "shape", "itemsize")

    def __init__(self, key, vlist, priority, seq):
        self.key = key
        self.data = [v._data for v in vlist]
        self.likes = vlist          # shape/dtype/context templates only
        self.priority = priority
        self.seq = seq
        self.shape = vlist[0].shape
        self.size = int(_np.prod(self.shape)) if self.shape else 1
        self.itemsize = vlist[0].dtype.itemsize

    @property
    def n_dev(self):
        return len(self.data)


class FusedBucketEngine:
    """Per-store pending queue + bucket planner + compiled-step cache."""

    def __init__(self, kv):
        self._kv = kv
        self._pending = []
        self._pending_keys = set()
        self._pending_bytes = 0
        self._seq = 0
        self._steps = {}     # bucket signature -> jitted step fn
        # flat error-feedback residuals: keys_tuple -> {"layout", "res":
        # [per-device jnp flat buffer]} — donated into the bucket program
        # each step; seeded from / spilled to the eager per-(key,dev)
        # dict so switching paths never loses accumulated residual
        self._flat_res = {}
        self.last_flush_buckets = []   # [[keys]] in dispatch order
        self.stats = {"flushes": 0, "buckets": 0, "keys": 0,
                      "bytes_pushed": 0}
        # comm/compute overlap (docs/KVSTORE.md "Overlapped push"):
        # _streaming marks dispatches issued by the mid-push streaming
        # flush (they overlap the rest of the backward walk by
        # construction); _overlap_t0 opens the per-walk overlap window
        # at the first such dispatch and the next end-of-push flush
        # closes it into kvstore_overlap_window_ms
        self._overlap = overlap_enabled()
        self._streaming = False
        self._overlap_t0 = None
        # in-launch numerics witness: donated f32 scalar accumulating
        # non-finite gradient elements across bucket programs; read only
        # at sync boundaries by publish_sentinels()
        self._nf_acc = None
        self._published_nf = 0.0

    # -- eligibility ----------------------------------------------------
    def _updater_mode(self):
        """None for assign mode, a fused signature tuple for a fusable
        optimizer Updater, or False when updates must stay eager."""
        from .optimizer import Updater
        updater = self._kv._updater
        if updater is None:
            return None
        if not isinstance(updater, Updater):
            return False
        sig = updater.optimizer._fused_bucket_sig()
        return sig if sig is not None else False

    def eligible(self, key, vlist, mode):
        """mode: the result of _updater_mode(), computed once per push
        call by the caller (it cannot change mid-call)."""
        return self.ineligible_reason(key, vlist, mode) is None

    def ineligible_reason(self, key, vlist, mode):
        """None when the push may take the compiled bucketed path, else
        a BOUNDED reason slug (it becomes a telemetry label on the
        ``kvstore_fallbacks`` counter — keep key names and shapes out)."""
        if mode is False:
            from .optimizer import Updater
            updater = self._kv._updater
            if not isinstance(updater, Updater):
                return "custom_updater"
            return ("unfused_optimizer:%s"
                    % type(updater.optimizer).__name__)
        for v in vlist:
            if not isinstance(v, NDArray):
                return "non_ndarray_value"
            if getattr(v, "stype", "default") != "default":
                return "sparse_value"
            if v.dtype != _np.float32:
                # low-precision values fuse only through an optimizer
                # apply (f32 master-gradient view); assign mode stays
                # f32 so stored dtypes can't silently change
                if mode is None or not _fused.is_low_precision(v.dtype):
                    return "non_f32_dtype"
            if v.shape != vlist[0].shape:
                return "mismatched_device_shapes"
        if mode is not None:
            stored = self._kv._store.get(key)
            if stored is None:
                return "key_not_initialized"
            if stored.dtype != vlist[0].dtype \
                    or stored.shape != vlist[0].shape:
                return "stored_value_mismatch"
            from .kvstore import _updater_key
            st = self._kv._updater.states.get(_updater_key(key))
            if st is not None:
                leaves, _ = _fused.flatten_state(st)
                if not all(isinstance(l, NDArray) for l in leaves):
                    return "non_fusable_optimizer_state"
        return None

    # -- queue ----------------------------------------------------------
    @property
    def has_pending(self):
        return bool(self._pending)

    def enqueue(self, key, vlist, priority):
        if key in self._pending_keys:
            # two pushes of the same key without a sync point: preserve
            # push-ordering semantics by flushing the first
            self.flush()
        it = _Pending(key, vlist, priority, self._seq)
        self._pending.append(it)
        self._pending_keys.add(key)
        self._pending_bytes += it.size * it.itemsize
        self._seq += 1
        # streaming flush: once a bucket's worth is pending, dispatch the
        # full buckets NOW (the partial tail stays pending) — enqueue
        # order (executor_group.push_order: backward gradient
        # availability) decides which buckets hit the device while the
        # host is still walking the remaining keys
        if self._pending_bytes >= bucket_byte_cap():
            self.flush(keep_partial=True)

    # -- planning -------------------------------------------------------
    def _pack(self, items):
        """Greedy size-capped packing in (priority desc, arrival) order;
        a new bucket starts when the cap would overflow or the device
        count or dtype changes (a bucket's flat wire layout is
        homogeneous); an oversized value gets its own bucket."""
        cap = bucket_byte_cap()
        buckets, cur, cur_bytes = [], [], 0
        for it in items:
            nbytes = it.size * it.itemsize
            if cur and (cur_bytes + nbytes > cap
                        or it.n_dev != cur[0].n_dev
                        or it.likes[0].dtype != cur[0].likes[0].dtype):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(it)
            cur_bytes += nbytes
            if cur_bytes >= cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            buckets.append(cur)
        return buckets

    # -- flush ----------------------------------------------------------
    def flush(self, keep_partial=False):
        """Dispatch pending pushes as compiled buckets (priority desc,
        then arrival). With ``keep_partial`` (the streaming path), a
        trailing bucket still below the byte cap stays pending so
        steady-state bucket shapes don't depend on where mid-push
        flushes landed."""
        if not keep_partial and self._overlap_t0 is not None:
            # the walk that opened an overlap window is landing its
            # final bucket: close the window (time comms had to hide)
            import time
            OVERLAP_WINDOW_MS.observe(
                (time.perf_counter() - self._overlap_t0) * 1e3)
            self._overlap_t0 = None
        if not self._pending:
            return
        items = sorted(self._pending, key=lambda it: (-it.priority, it.seq))
        self._pending = []
        self._pending_keys.clear()
        self._pending_bytes = 0
        buckets = self._pack(items)
        if keep_partial and buckets:
            cap = bucket_byte_cap()
            tail = buckets[-1]
            if sum(it.size * it.itemsize for it in tail) < cap:
                buckets = buckets[:-1]
                for it in tail:
                    self._pending.append(it)
                    self._pending_keys.add(it.key)
                    self._pending_bytes += it.size * it.itemsize
            if not buckets:
                return
        self.last_flush_buckets = [[it.key for it in b] for b in buckets]
        items = [it for b in buckets for it in b]
        mode = self._updater_mode()
        if keep_partial and self._overlap and self._overlap_t0 is None:
            import time
            self._overlap_t0 = time.perf_counter()
        self._streaming = keep_partial
        try:
            for bucket in buckets:
                self._dispatch(bucket, mode)
        finally:
            self._streaming = False
        comp = self._kv._compression
        nbytes = sum(it.size * it.itemsize * it.n_dev for it in items)
        self.stats["flushes"] += 1
        self.stats["buckets"] += len(buckets)
        self.stats["keys"] += len(items)
        self.stats["bytes_pushed"] += nbytes
        BYTES_PUSHED.increment(nbytes)
        # logical wire ratio of the active config (orig bits / 2-bit);
        # the local store never materializes packed bytes, so this is
        # nominal by construction — see docs/KVSTORE.md
        COMPRESS_RATIO.set_value(
            items[0].itemsize * 8 / 2.0 if comp is not None else 1.0)
        BUCKET_COUNT.set_value(len(buckets))

    def _dispatch(self, bucket, mode):
        from .executor import _count_dispatch
        _count_dispatch()       # one compiled bucket program per call
        if self._streaming and self._overlap:
            # dispatched before the final backward bucket landed: the
            # program (XLA-async; the tpu host transport's wire rides
            # the pipeline thread) overlaps the rest of the walk
            OVERLAP_DISPATCHES.inc()
        return _SITE.timed(self._dispatch_inner, bucket, mode,
                           dispatch_hist=DISPATCH_MS)

    def synchronize(self):
        """Block until every dispatched bucket's side effects are
        visible on this host. The base engine's dispatches are XLA-async
        only (jax arrays synchronize at first read), so this is a no-op;
        the tpu engine overrides it to drain its pipelined wire thread.
        Called by the kvstore's sync points (pull/barrier/state save)."""

    def publish_sentinels(self):
        """Fold the donated non-finite witness scalar into the shared
        ``nonfinite_grads`` counter. Reading the scalar is a HOST SYNC —
        this runs only from existing sync boundaries (Module._fit_sync,
        kvstore pull/barrier), never the per-step dispatch path.
        Returns the cumulative count, or None when no witness rode a
        program yet (sentinels off, or nothing dispatched)."""
        acc = self._nf_acc
        if acc is None:
            return None
        # analyze: ok(hostsync) sentinel publish rides an existing sync boundary (_fit_sync / kvstore pull), never the per-dispatch path
        cum = float(_np.asarray(acc))
        delta = int(round(cum - self._published_nf))
        if delta > 0:
            self._published_nf = cum
            from .telemetry import sentinel as _sentinel
            _sentinel.NONFINITE_GRADS.inc(delta)
            from .telemetry.flight import RECORDER
            RECORDER.note("sentinel_trip", source="kvstore_bucket",
                          nonfinite=delta)
        return cum

    def _updater_inputs(self, bucket):
        """Collect the live optimizer-apply inputs for one bucket (and
        perform the per-key update-count side effects) — shared by the
        single-process bucket program and the tpu kvstore's cross-host
        programs (kvstore_tpu/engine.py) so keying/lr/wd semantics can
        never drift between them."""
        from .kvstore import _updater_key
        kv = self._kv
        updater = kv._updater
        opt = updater.optimizer
        ukeys = [_updater_key(it.key) for it in bucket]
        weights_nd, state_leaves, tpls, mp_flags = [], [], [], []
        for it, uk in zip(bucket, ukeys):
            w = kv._store[it.key]
            if uk not in updater.states:
                updater.states[uk] = opt.create_state_multi_precision(
                    uk, w)
                updater.states_synced[uk] = True
            weights_nd.append(w)
            leaves, tpl = _fused.flatten_state(updater.states[uk])
            state_leaves.append(leaves)
            tpls.append(tpl)
            # multi-precision is an EXPLICIT static flag (an Adam
            # (mean, var) pair is structurally ambiguous with an
            # (inner, weight32) master tuple)
            mp_flags.append(bool(opt.multi_precision)
                            and _fused.is_low_precision(w.dtype))
        lr_vec, wd_vec, extra = opt._fused_runtime(ukeys)
        use_wd = bool(_np.any(wd_vec != 0.0))
        return (weights_nd, state_leaves, tuple(tpls), tuple(mp_flags),
                lr_vec, wd_vec, extra, use_wd,
                _np.float32(opt.rescale_grad))

    def _dispatch_inner(self, bucket, mode):
        kv = self._kv
        comp = kv._compression
        threshold = comp.threshold if comp is not None else None
        n_dev = bucket[0].n_dev
        assert mode is not False

        layout, off = [], 0
        for it in bucket:
            layout.append((off, it.size, it.shape))
            off += it.size
        layout = tuple(layout)

        # CommDevice gather: device-committed gradients move to the
        # bucket's reduce device so the single program has one placement
        # (uncommitted and mesh-sharded arrays pass through untouched)
        dev0 = _single_device(bucket[0].data[0])
        grads = tuple(tuple(_on_device(it.data[d], dev0)
                            for it in bucket) for d in range(n_dev))
        residuals, keys_tuple = (), None
        if comp is not None:
            keys_tuple = tuple(it.key for it in bucket)
            residuals = self._flat_residuals(keys_tuple, layout, n_dev,
                                             bucket)

        ctx0 = bucket[0].likes[0].context
        sent = _telemetry.sentinel.numerics_enabled()
        nf = None
        if sent:
            nf = self._nf_acc
            if nf is None:
                nf = jnp.zeros((), jnp.float32)
            nf = _on_device(nf, dev0)
        if mode is None:
            sig = (None, threshold, n_dev, layout, sent)
            fn = self._steps.get(sig)
            if fn is None:
                fn = self._steps[sig] = _build_step(
                    layout, n_dev, threshold, None, None, None, False,
                    sentinel=sent)
                _telemetry.programs.record(
                    "kvstore_bucket", fn,
                    (residuals, grads, nf) if sent
                    else (residuals, grads))
            if sent:
                outs, new_res, self._nf_acc = fn(residuals, grads, nf)
            else:
                outs, new_res = fn(residuals, grads)
            for it, out in zip(bucket, outs):
                kv._store[it.key] = NDArray(out, ctx0)
        else:
            (weights_nd, state_leaves, tpls, mp_flags, lr_vec, wd_vec,
             extra, use_wd, rescale) = self._updater_inputs(bucket)
            sig = (mode, threshold, n_dev, layout, tpls, mp_flags,
                   use_wd, sent)
            fn = self._steps.get(sig)
            fresh = fn is None
            if fresh:
                fn = self._steps[sig] = _build_step(
                    layout, n_dev, threshold, mode, tpls, mp_flags,
                    use_wd, sentinel=sent)
            weights = tuple(w._data for w in weights_nd)
            states = tuple(tuple(l._data for l in leaves)
                           for leaves in state_leaves)
            if fresh:
                _telemetry.programs.record(
                    "kvstore_bucket", fn,
                    (weights, states, residuals, grads, lr_vec, wd_vec,
                     rescale, extra, nf) if sent
                    else (weights, states, residuals, grads, lr_vec,
                          wd_vec, rescale, extra))
            if sent:
                new_ws, new_ss, new_res, self._nf_acc = fn(
                    weights, states, residuals, grads, lr_vec, wd_vec,
                    rescale, extra, nf)
            else:
                new_ws, new_ss, new_res = fn(
                    weights, states, residuals, grads, lr_vec, wd_vec,
                    rescale, extra)
            for w, leaves, nw, ns in zip(weights_nd, state_leaves,
                                         new_ws, new_ss):
                w._set_data(nw)
                for l, nl in zip(leaves, ns):
                    l._set_data(nl)
        if keys_tuple is not None:
            self._flat_res[keys_tuple]["res"] = list(new_res)

    # -- flat error-feedback residuals ---------------------------------
    def _flat_residuals(self, keys_tuple, layout, n_dev, bucket):
        """Donated flat residual buffers for a bucket, one per device.
        First use seeds each buffer from the eager per-(key,dev) residual
        dict (zeros when absent) and takes ownership of those entries; a
        layout/device-count change spills back first so no accumulated
        residual is ever lost."""
        rec = self._flat_res.get(keys_tuple)
        if rec is not None and (rec["layout"] != layout
                                or len(rec["res"]) != n_dev):
            self.spill_residuals()
            rec = None
        if rec is None and self._flat_res:
            # a changed bucket composition may hold some of these keys'
            # residuals inside other flat records — spill everything back
            # to the per-key dict so seeding below picks them up
            ours = set(keys_tuple)
            if any(ours.intersection(kt) for kt in self._flat_res):
                self.spill_residuals()
        if rec is None:
            kv = self._kv
            dev0 = _single_device(bucket[0].data[0])
            res = []
            for d in range(n_dev):
                # residuals live in f32 (the master-gradient view)
                # regardless of the gradient dtype; the cast is a no-op
                # for f32 and defends against pre-f32 restored state
                parts = [_on_device(
                    kv._get_residual((it.key, d), it.likes[d])._data,
                    dev0).reshape(-1).astype(jnp.float32)
                    for it in bucket]
                res.append(parts[0] if len(parts) == 1
                           else jnp.concatenate(parts))
                for it in bucket:
                    kv._compression_residuals.pop((it.key, d), None)
            rec = self._flat_res[keys_tuple] = {"layout": layout,
                                                "res": res}
        return tuple(rec["res"])

    def spill_residuals(self):
        """Write flat residuals back to the eager per-(key,dev) dict (as
        NDArrays) — called before anything that may reroute keys to the
        eager path (updater/compression/bucketing changes)."""
        kv = self._kv
        for keys_tuple, rec in self._flat_res.items():
            for d, flat in enumerate(rec["res"]):
                for key, (off, size, shape) in zip(keys_tuple,
                                                   rec["layout"]):
                    seg = flat[off:off + size].reshape(shape)
                    kv._compression_residuals[(key, d)] = NDArray(seg)
        self._flat_res.clear()
