"""Executor: a bound symbol compiled to whole-graph XLA computations.

Reference parity: src/executor/graph_executor.cc + include/mxnet/executor.h.
The reference builds a full fwd+bwd nnvm graph, plans memory, and pushes one
engine op per node; here ``simple_bind`` traces the DAG once into

* ``_fwd``      — one XLA computation for forward (+ aux-state updates),
* ``_fwd_bwd``  — one XLA computation for forward+backward via ``jax.vjp``,

so the whole step is a single fused HLO (the BASELINE.json north-star:
"one XLA computation per forward/backward subgraph"). Memory planning,
op fusion, scheduling = XLA. grad_req add/write follows the reference's
OpReqType semantics (include/mxnet/op_attr_types.h:46).

Training forward is lazily fused: ``forward(is_train=True)`` defers
execution; ``backward()`` then runs the fused fwd+bwd program, so a
Module-style fit step costs exactly one compiled program launch.
"""
from __future__ import annotations


import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context, current_context
from .ndarray.ndarray import NDArray, zeros as nd_zeros
from .ops import registry as _reg
from . import telemetry as _telemetry

__all__ = ["Executor"]

# retrace witness: incremented at TRACE time inside every executor
# program body (host code that only runs while jax traces), so a
# steady-state launch leaves it untouched — same contract as the
# kvstore/fused-fit TRACE_COUNTs (docs/OBSERVABILITY.md)
EXECUTOR_RETRACES = _telemetry.REGISTRY.counter(
    "executor_retraces",
    "executor fwd/fwd_bwd/monitor program (re)traces", vital=True)
EXECUTOR_DISPATCH_MS = _telemetry.REGISTRY.histogram(
    "executor_dispatch_ms",
    "host wall time to dispatch one executor program (async enqueue, "
    "not device completion)", unit="ms")
# dispatch + retrace instrumentation site (shared RetraceSite
# semantics with kvstore_fused / fused_fit): traced bodies call
# _note_retrace(); call sites dispatch through _timed_dispatch
_SITE = _telemetry.RetraceSite(EXECUTOR_RETRACES,
                               _telemetry.JIT_COMPILE_MS,
                               site="executor")
_note_retrace = _SITE.note


# per-thread launch tally next to the global one: lets a dispatcher
# (the decode engine) attribute launch counts to ITS OWN calls even
# while other threads (serving replicas, checkpoint) dispatch
# concurrently — same rationale as RetraceSite's TraceTally
_DISPATCH_TALLY = _telemetry.TraceTally()


def _count_dispatch():
    """Bump the global device-launch witness (profiler.DEVICE_DISPATCHES)
    — bench.py --mode train reads deltas for train_dispatches_per_step."""
    from . import profiler as _prof
    _prof.DEVICE_DISPATCHES.increment()
    _DISPATCH_TALLY.count += 1


def _timed_dispatch(fn, *args):
    """Call one jitted executor program with telemetry: dispatch wall
    time -> executor_dispatch_ms; calls during which this thread
    (re)traced additionally observe into jit_compile_ms."""
    return _SITE.timed(fn, *args, dispatch_hist=EXECUTOR_DISPATCH_MS)


def _build_graph_fn(symbol, collect_taps=False, monitor_all=False,
                    group_devices=None, tap_cb=None, tap_stat=None):
    """Build a pure function (args, auxs, seed, is_train) ->
    (outputs, new_auxs) interpreting the DAG with registered op impls.
    With ``collect_taps`` the function also returns {tap_name: value} for
    every op output (and every variable when ``monitor_all``) — the debug
    program behind executor monitor callbacks (reference
    graph_executor.cc SetMonitorCallback).

    With ``tap_cb`` the taps instead STREAM out of the compiled program
    via ``jax.debug.callback`` — the TPU-native analog of the reference
    engine firing the monitor callback per executed op: ONE program, no
    second tapped launch. ``tap_stat`` (a jnp function) is applied to
    each tap inside the program, so only the small statistic crosses to
    the host, not the full intermediate tensor.

    ``group_devices`` maps a ctx_group name (``with AttrScope(
    ctx_group='dev1')``) to a ``jax.Device``: nodes carrying that attr
    have their outputs placed on the group's device via ``jax.device_put``
    **inside the traced program** — the TPU-native realization of the
    reference's PlaceDevice pass + _CrossDeviceCopy insertion
    (graph_executor.cc:408): one XLA program spanning the devices, with
    transfers exactly at group boundaries, and gradients transferring
    back through the transposed copies."""
    topo = symbol._topo()
    entries = list(symbol._entries)
    aux_names = set(symbol.list_auxiliary_states())

    # activation sharding constraints: __sharding__ attrs on op outputs
    # become jax.lax.with_sharding_constraint inside the ONE program.
    # The mesh is captured at build time — safe because _compiled_cache
    # keys program caches on sharding.active_fingerprint(symbol).
    from . import sharding as _sharding
    _smesh = _sharding.get_mesh()
    _constraints = {}
    if _smesh is not None:
        for _node in topo:
            if _node.is_var:
                continue
            _s = _node.str_attrs.get(_sharding.SHARDING_ATTR)
            if _s:
                _constraints[id(_node)] = _sharding.parse_spec(_s)
        _sharding.CONSTRAINT_SITES.set(len(_constraints))

    def _constrain(node, v):
        entries_ = _constraints.get(id(node))
        if entries_ is None:
            return v
        # divisibility surfaces at trace time, when shapes are known
        _sharding.check_divisible(entries_, v.shape, _smesh,
                                  what="output of %r" % node.name)
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(_smesh, PartitionSpec(*entries_)))

    def _place(node, v):
        if not group_devices:
            return v
        grp = node.str_attrs.get("ctx_group")
        dev = group_devices.get(grp)
        return jax.device_put(v, dev) if dev is not None else v

    def _emit_tap(name, v):
        import functools
        val = tap_stat(v) if tap_stat is not None else v
        jax.debug.callback(functools.partial(tap_cb, name), val)

    def _tap_count(node):
        # taps follow the user-visible monitor contract: one entry per
        # visible output (invisible aux outputs like BatchNorm's
        # moving-stat updates would appear as duplicate same-named taps)
        return node.visible_out_count()

    def graph_fn(args, auxs, seed, is_train):
        rng = jax.random.key(seed)
        new_auxs = {}
        taps = {}
        with _reg._OpCtxScope(is_train, rng):
            env = {}
            for node in topo:
                if node.is_var:
                    if node.name in args:
                        env[(id(node), 0)] = _place(node, args[node.name])
                    elif node.name in auxs:
                        env[(id(node), 0)] = _place(
                            node, jax.lax.stop_gradient(auxs[node.name]))
                    else:
                        raise MXNetError("unbound variable '%s'" % node.name)
                    if collect_taps and monitor_all:
                        taps[node.name] = env[(id(node), 0)]
                    if tap_cb is not None and monitor_all:
                        _emit_tap(node.name, env[(id(node), 0)])
                    continue
                ins = [env[(id(inp), oi)] for inp, oi in node.inputs]
                raw = node.op.fn(*ins, **node.attrs)
                if group_devices:
                    raw = (tuple(_place(node, r) for r in raw)
                           if isinstance(raw, (tuple, list))
                           else _place(node, raw))
                outs = list(raw) if isinstance(raw, (tuple, list)) else [raw]
                if _constraints:
                    # the annotation names the node's primary output
                    outs[0] = _constrain(node, outs[0])
                n_vis = _tap_count(node)
                for i, v in enumerate(outs):
                    env[(id(node), i)] = v
                    if collect_taps and i < n_vis:
                        taps[node.output_name(i)] = v
                    if tap_cb is not None and i < n_vis:
                        _emit_tap(node.output_name(i), v)
                # aux-state updates (reference FMutateInputs)
                if node.op.mutate_inputs and is_train:
                    in_names = node.op.input_names
                    for mut_name, out_idx in node.op.mutate_inputs:
                        for (inp, _), nm in zip(node.inputs, in_names):
                            if nm == mut_name and inp.is_var and inp.name in aux_names:
                                new_auxs[inp.name] = outs[out_idx]
            outputs = [env[(id(n), oi)] for n, oi in entries]
        for name in auxs:
            new_auxs.setdefault(name, auxs[name])
        if collect_taps:
            return outputs, new_auxs, taps
        return outputs, new_auxs

    return graph_fn


def _compiled_cache(symbol):
    """Per-symbol compiled-callable cache: executors bound to the same
    Symbol (rebinds, numeric-grad perturbations, BucketingModule buckets)
    share XLA executables — the analog of the reference's shared memory
    pool across executors (graph_executor.cc InitDataEntryMemory).

    The store is keyed by ``sharding.active_fingerprint(symbol)``: None
    for mesh-independent symbols (the common case — one entry, exactly
    the old behavior), or the selected mesh's fingerprint when the
    symbol carries ``__sharding__`` annotations, whose graph_fn closes
    over the mesh.  A mesh change then builds fresh programs instead of
    silently reusing executables with stale shardings."""
    from . import sharding as _sharding
    store = getattr(symbol, "_exec_cache", None)
    if store is None:
        store = symbol._exec_cache = {}
    fp = _sharding.active_fingerprint(symbol)
    cache = store.get(fp)
    if cache is None:
        graph_fn = _build_graph_fn(symbol)

        @jax.jit
        # analyze: ok(retrace) graph_fn is symbol-pure; the compiled cache lives on the Symbol itself (_exec_cache)
        def _fwd_train(args, auxs, seed):
            _note_retrace()
            return graph_fn(args, auxs, seed, True)

        @jax.jit
        # analyze: ok(retrace) graph_fn is symbol-pure; the compiled cache lives on the Symbol itself (_exec_cache)
        def _fwd_eval(args, auxs, seed):
            _note_retrace()
            outs, _ = graph_fn(args, auxs, seed, False)
            return outs

        cache = {"graph_fn": graph_fn, "fwd_train": _fwd_train,
                 "fwd_eval": _fwd_eval, "fwd_eval_donated": None,
                 "fwd_bwd": {}, "fwd_monitor": {}}
        store[fp] = cache
    return cache


def _make_fwd_eval_donated(graph_fn):
    """Inference-forward program whose FIRST argument pytree (a dict of
    donated inputs) hands its buffers to XLA for in-place reuse.  The
    decode engine routes the paged k/v caches here (donate_args), so
    each compiled step updates the caches where they live instead of
    copying the whole cache in and out every launch — the O(cache)
    per-token traffic docs/DECODE.md used to book as an accepted cost.
    ONE callable serves any donated/retained name split: jit keys on
    the pytree structure of both dicts, and the distinct ``fn_name``
    lets telemetry.programs() tell donated programs from copy-based
    ones."""
    def _fwd_eval_donated(donated, args, auxs, seed):
        _note_retrace()
        outs, _ = graph_fn(dict(args, **donated), auxs, seed, False)
        return outs
    fn = jax.jit(_fwd_eval_donated, donate_argnums=0)
    _telemetry.programs.note_donation(fn, (0,))
    return fn


class _StreamTarget:
    """Indirection for in-stream tap callbacks: the compiled stream
    program calls the module-level dispatcher, which forwards to
    whichever executor is currently running it — so the compiled program
    is executor-independent and can be cached per SYMBOL (like
    _compiled_cache), not per executor. A plain attribute, NOT
    thread-local: jax delivers debug callbacks on a runtime thread, so
    the running executor is published globally for the duration of the
    monitored launch (which ends with an effects barrier). Concurrent
    monitored launches from multiple host threads would interleave taps
    — a debug-path limitation the reference's engine callbacks share."""
    exe = None


_STREAM_TARGET = _StreamTarget()

# the stable default on-device statistic (mean |x|, the reference
# Monitor default); Monitor.install passes this same object so the
# stream-program cache key is stable across installs
def DEFAULT_STREAM_STAT(a):
    return jnp.mean(jnp.abs(a.astype(jnp.float32)))


def _stream_dispatch(name, value):
    exe = _STREAM_TARGET.exe
    if exe is not None:
        exe._stream_tap(name, value)


def _monitor_fn(symbol, is_train, monitor_all):
    """Jitted tapped-forward program, cached per (is_train, monitor_all)."""
    cache = _compiled_cache(symbol)
    key = (bool(is_train), bool(monitor_all))
    fn = cache["fwd_monitor"].get(key)
    if fn is None:
        tapped = _build_graph_fn(symbol, collect_taps=True,
                                 monitor_all=monitor_all)

        @jax.jit
        # analyze: ok(retrace) tapped graph is (symbol, is_train, monitor_all)-pure and cached under exactly that key
        def fn(args, auxs, seed):
            _note_retrace()
            return tapped(args, auxs, seed, is_train)

        cache["fwd_monitor"][key] = fn
    return fn


def _make_fwd_bwd(graph_fn, diff_names, mirror):
    # `mirror` (MXNET_BACKWARD_DO_MIRROR) is an explicit builder param
    # and part of every fwd_bwd cache key: a capture read from the
    # environment here would be invisible to the cache, so flipping the
    # knob between binds would silently reuse the wrong program
    # (flagged by mx.analyze retrace/env-capture)

    @jax.jit
    def _fwd_bwd(args, auxs, seed, ograds):
        _note_retrace()
        diff = {n: args[n] for n in diff_names}
        rest = {n: v for n, v in args.items() if n not in diff}

        def f(d):
            outs, new_auxs = graph_fn({**rest, **d}, auxs, seed, True)
            return outs, new_auxs

        if mirror:
            # MXNET_BACKWARD_DO_MIRROR: recompute the forward during
            # backward instead of keeping activations (jax.checkpoint —
            # the reference's gradient-mirroring memory/compute trade,
            # graph_executor.cc:193)
            f = jax.checkpoint(f)

        outs, vjp_fn, new_auxs = jax.vjp(f, diff, has_aux=True)
        # head grads cast to each output's dtype (a bf16/fp16 graph fed
        # f32 out_grads — e.g. check_consistency's shared grads — must
        # not fail the VJP dtype check)
        cts = [jnp.asarray(g, o.dtype) if g is not None
               else jnp.ones_like(o)
               for g, o in zip(ograds, outs)]
        (grads,) = vjp_fn(cts)
        return outs, new_auxs, grads
    return _fwd_bwd


class Executor:
    def __init__(self, symbol, ctx, arg_dict, grad_dict, aux_dict,
                 grad_req_dict, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx if ctx is not None else current_context()
        self.arg_dict = arg_dict
        self.grad_dict = grad_dict
        self.aux_dict = aux_dict
        self._grad_req = grad_req_dict
        # group2ctx is the reference's manual model-parallel placement
        # (graph_executor.cc PlaceDevice + _CrossDeviceCopy insertion).
        # TPU-native realization: each ctx_group's jax device is honored
        # by jax.device_put at group boundaries INSIDE the one traced
        # program (_build_graph_fn group_devices) — XLA compiles a single
        # multi-device program with transfers exactly where the reference
        # inserted copy nodes, and gradients ride the transposed copies.
        self._group2ctx = group2ctx
        self._group_devices = None
        if group2ctx:
            base = ctx if ctx is not None else current_context()
            gd = {g: c.jax_device for g, c in group2ctx.items()}
            if any(c != base for c in group2ctx.values()):
                self._group_devices = gd
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self._diff_names = [n for n in self._arg_names
                            if grad_req_dict.get(n, "null") != "null"]
        self._monitor_callback = None
        self._monitor_all = False
        self._monitor_mode = "stream"
        self._monitor_stat = None
        self._donated_names = ()
        self._jit_fwd_eval_donated = None
        self._outputs = None
        self._pending_train_fwd = False
        self._train_seed = None
        self._train_auxs = None
        self._step = 0
        from . import random as _rand
        self._base_seed = _rand.next_seed()

        from . import config as _config
        # snapshot MXNET_BACKWARD_DO_MIRROR at BIND time: every fwd_bwd
        # this executor selects (plain or stream-monitored) uses this
        # one setting, and it is part of each cache key — a mid-life
        # env flip affects only later binds, never an existing executor
        self._mirror = mirror = _config.backward_do_mirror()
        if self._group_devices is None:
            cache = _compiled_cache(symbol)
            self._graph_fn = cache["graph_fn"]
            self._jit_fwd_train = cache["fwd_train"]
            self._jit_fwd_eval = cache["fwd_eval"]
            key = (tuple(sorted(self._diff_names)), mirror)
            if key not in cache["fwd_bwd"]:
                cache["fwd_bwd"][key] = _make_fwd_bwd(
                    cache["graph_fn"], key[0], mirror)
            self._jit_fwd_bwd = cache["fwd_bwd"][key]
        else:
            # model-parallel bind: the placed program is specific to this
            # group->device map, so it gets its own jitted callables
            # (cached per symbol+placement)
            gkey = tuple(sorted((g, str(d))
                                for g, d in self._group_devices.items()))
            placed = getattr(symbol, "_exec_cache_placed", None)
            if placed is None:
                placed = symbol._exec_cache_placed = {}
            entry = placed.get(gkey)
            if entry is None:
                graph_fn = _build_graph_fn(
                    symbol, group_devices=self._group_devices)

                @jax.jit
                # analyze: ok(retrace) placed graph_fn is (symbol, group->device map)-pure; cache keyed by that placement
                def _fwd_train(args, auxs, seed):
                    _note_retrace()
                    return graph_fn(args, auxs, seed, True)

                @jax.jit
                # analyze: ok(retrace) placed graph_fn is (symbol, group->device map)-pure; cache keyed by that placement
                def _fwd_eval(args, auxs, seed):
                    _note_retrace()
                    outs, _ = graph_fn(args, auxs, seed, False)
                    return outs

                entry = {"graph_fn": graph_fn, "fwd_train": _fwd_train,
                         "fwd_eval": _fwd_eval, "fwd_bwd": {}}
                placed[gkey] = entry
            self._graph_fn = entry["graph_fn"]
            self._jit_fwd_train = entry["fwd_train"]
            self._jit_fwd_eval = entry["fwd_eval"]
            key = (tuple(sorted(self._diff_names)), mirror)
            if key not in entry["fwd_bwd"]:
                entry["fwd_bwd"][key] = _make_fwd_bwd(
                    entry["graph_fn"], key[0], mirror)
            self._jit_fwd_bwd = entry["fwd_bwd"][key]

    # ------------------------------------------------------------------
    @property
    def outputs(self):
        if self._pending_train_fwd:
            self._run_fwd(True)
        return self._outputs

    @property
    def output_dict(self):
        return dict(zip(self._output_names, self.outputs))

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    def set_monitor_callback(self, callback, monitor_all=False,
                             mode="stream", stat_fn=None):
        """Install a (name, NDArray) callback fired with every node output
        (and every variable when ``monitor_all``) after each forward
        (reference graph_executor.cc SetMonitorCallback).

        ``mode='stream'`` (default) fires the taps from INSIDE the one
        compiled step via ``jax.debug.callback`` — the analog of the
        reference engine streaming callbacks from in-flight execution.
        ``stat_fn`` (a jnp function) runs on-device per tap so only the
        statistic crosses to the host; without it the full tensors
        stream out. Monitored batches cost ~the plain step plus the
        stats (timed in tests/test_monitor_stream.py).

        ``mode='tapped'`` keeps the previous behavior — a SECOND jitted
        program returning every intermediate (full-tensor dumps without
        per-tap host callbacks) at ~2x step cost on monitored batches.
        Monitor's interval gate (``Monitor(interval=N)``) limits either
        cost to every N-th batch."""
        self._monitor_callback = callback
        self._monitor_all = bool(monitor_all)
        self._monitor_mode = mode
        self._monitor_stat = stat_fn

    def _stream_tap(self, name, value):
        cb = self._monitor_callback
        if cb is not None:
            cb(name, NDArray(jnp.asarray(value), self._ctx))

    def _stream_fns(self):
        """Jitted in-stream-tapped programs. Cached per SYMBOL (sharing
        XLA executables across executors and re-installs exactly like
        _compiled_cache) — the compiled program calls the module-level
        _stream_dispatch, which forwards to the currently-running
        executor. Keyed by (monitor_all, stat id, diff set); Monitor
        passes the stable DEFAULT_STREAM_STAT object, so repeat installs
        hit the cache. group2ctx (placed) binds keep a per-executor
        cache since their programs embed the device map."""
        key = (self._monitor_all, id(self._monitor_stat))
        if self._group_devices is None:
            store = _compiled_cache(self._symbol).setdefault("stream", {})
        else:
            store = self.__dict__.setdefault("_placed_stream_cache", {})
        fns = store.get(key)
        if fns is None:
            tapped = _build_graph_fn(
                self._symbol, group_devices=self._group_devices,
                monitor_all=self._monitor_all, tap_cb=_stream_dispatch,
                tap_stat=self._monitor_stat)

            @jax.jit
            # analyze: ok(retrace) stream-tap debug program: (symbol, monitor_all, stat)-pure, cached under that key; retraces intentionally uncounted on the monitored path
            def fwd_train(args, auxs, seed):
                return tapped(args, auxs, seed, True)

            @jax.jit
            # analyze: ok(retrace) stream-tap debug program: (symbol, monitor_all, stat)-pure, cached under that key; retraces intentionally uncounted on the monitored path
            def fwd_eval(args, auxs, seed):
                outs, _ = tapped(args, auxs, seed, False)
                return outs

            # "stat" pins the stat function alive so its id() (the cache
            # key) can never be recycled onto a different function
            fns = {"graph_fn": tapped, "fwd_train": fwd_train,
                   "fwd_eval": fwd_eval, "fwd_bwd": {},
                   "stat": self._monitor_stat}
            store[key] = fns
        # forward programs are diff-set independent; only the fused
        # fwd+bwd needs a per-(diff-set, mirror) variant — using the
        # BIND-time mirror snapshot so a monitored backward can never
        # run a different mirror setting than this executor's plain one
        mirror = self._mirror
        diff_key = (tuple(sorted(self._diff_names)), mirror)
        if diff_key not in fns["fwd_bwd"]:
            fns["fwd_bwd"][diff_key] = _make_fwd_bwd(
                fns["graph_fn"], diff_key[0], mirror)
        return {"fwd_train": fns["fwd_train"], "fwd_eval": fns["fwd_eval"],
                "fwd_bwd": fns["fwd_bwd"][diff_key]}

    def _monitor_active(self):
        if self._monitor_callback is None:
            return False
        # Monitor attaches itself to its stat_helper; skip the extra tapped
        # program launch entirely on batches its interval gate would drop
        mon = getattr(self._monitor_callback, "_monitor", None)
        return mon is None or getattr(mon, "activated", True)

    def _fire_monitor(self, is_train, seed, auxs):
        fn = _monitor_fn(self._symbol, is_train, self._monitor_all)
        _, _, taps = fn(self._args_values(), auxs, seed)
        # a stream-installed callback expects the on-device statistic,
        # not the raw tensor (Monitor.stream_helper skips stat_func) —
        # apply it here when the tapped program is used as a fallback
        # (e.g. MXNET_BACKWARD_DO_MIRROR)
        stat = self._monitor_stat if self._monitor_mode == "stream" else None
        for name, val in taps.items():
            if stat is not None:
                val = stat(val)
            self._monitor_callback(name, NDArray(val, self._ctx))

    # ------------------------------------------------------------------
    def _args_values(self):
        return {n: self.arg_dict[n]._data for n in self._arg_names}

    def _auxs_values(self):
        return {n: self.aux_dict[n]._data for n in self._aux_names}

    def _next_seed(self):
        self._step += 1
        return _np.uint32((int(self._base_seed) + self._step * 2654435761)
                          & 0x7FFFFFFF)

    def _to_ctx(self, data):
        """Colocate an input with the executor's device — data-iterator
        batches live on the cpu context (reference iterator contract) and
        must move to the bind device exactly once here."""
        dev = self._ctx.jax_device
        try:
            if data.devices() == {dev}:
                return data
        except AttributeError:
            pass
        import jax as _jax
        return _jax.device_put(data, dev)

    def donate_args(self, names):
        """Route the named arguments through the donated inference
        forward: their device buffers are handed to XLA each eval
        dispatch (donate_argnums), so programs that thread state through
        outputs (the decode engine's k/v caches) update it in place
        instead of copying it in and out every launch.

        CONTRACT: after every dispatch the donated NDArrays hold
        DELETED buffers — the caller must re-point them at the
        corresponding outputs (engine._commit_caches) before anything
        reads them.  Stream-monitored debug forwards fall back to the
        copy-based program.  Pass an empty sequence to turn donation
        back off.

        With the persistent compilation cache enabled the request is
        REFUSED (copy path kept, returns False): disk-loaded donated
        executables corrupt their buffers on this jax version
        (``mxnet_tpu.aot.store.donation_safe``, docs/AOT.md)."""
        names = tuple(names)
        for n in names:
            if n not in self.arg_dict:
                raise MXNetError("donate_args: unknown argument '%s'" % n)
        if not names:
            self._donated_names = ()
            self._jit_fwd_eval_donated = None
            return True
        from .aot import store as _aot_store
        if not _aot_store.donation_safe():
            import logging
            logging.getLogger(__name__).warning(
                "donate_args: refused — the persistent compilation "
                "cache is active and disk-loaded donated executables "
                "corrupt memory on this jax version; keeping the "
                "copy-based forward (docs/AOT.md)")
            self._donated_names = ()
            self._jit_fwd_eval_donated = None
            return False
        if self._group_devices is not None:
            raise MXNetError("donate_args: model-parallel (group2ctx) "
                             "binds are not supported")
        cache = _compiled_cache(self._symbol)
        if cache["fwd_eval_donated"] is None:
            cache["fwd_eval_donated"] = _make_fwd_eval_donated(
                cache["graph_fn"])
        self._donated_names = names
        self._jit_fwd_eval_donated = cache["fwd_eval_donated"]
        return True

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("forward: unknown argument '%s'" % k)
            dst = self.arg_dict[k]
            if isinstance(v, NDArray):
                data = v._data
                sh = dst._data.sharding
                if getattr(data, "sharding", None) != sh:
                    # move onto the bound buffer's placement (single
                    # device normally; the mesh under GSPMD binds)
                    data = jax.device_put(data, sh)
                dst._set_data(data)
            else:
                dst._sync_copyfrom(v)
        if is_train:
            # defer: backward() will run the fused fwd+bwd program. The seed
            # and pre-update aux snapshot are fixed NOW so that a forced
            # .outputs read and the later backward() see the exact same
            # computation (same dropout masks, single aux-momentum update).
            self._pending_train_fwd = True
            self._outputs = None
            self._train_seed = self._next_seed()
            self._train_auxs = self._auxs_values()
        else:
            self._train_seed = None
            self._train_auxs = None
            self._run_fwd(False)
        return self.outputs if not is_train else _LazyOutputs(self)

    @staticmethod
    def _prof_scope(name):
        from . import profiler as _prof
        if _prof.SYMBOLIC_ON:
            return _prof.scope(name, "symbolic")
        import contextlib
        return contextlib.nullcontext()

    def _run_fwd(self, is_train):
        monitored = self._monitor_active()
        stream = monitored and self._monitor_mode == "stream"
        if stream:
            # analyze: ok(threads) documented debug-path limitation: the running executor is published globally for the duration of a monitored launch (_StreamTarget docstring)
            _STREAM_TARGET.exe = self
        try:
            if is_train:
                seed = self._train_seed if self._train_seed is not None \
                    else self._next_seed()
                auxs = self._train_auxs if self._train_auxs is not None \
                    else self._auxs_values()
                if monitored and not stream:
                    self._fire_monitor(True, seed, auxs)
                fwd = (self._stream_fns()["fwd_train"] if stream
                       else self._jit_fwd_train)
                with self._prof_scope("Executor::forward"):
                    _count_dispatch()
                    outs, new_auxs = _timed_dispatch(
                        fwd, self._args_values(), auxs, seed)
                self._write_auxs(new_auxs)
            else:
                seed = self._next_seed()
                if monitored and not stream:
                    self._fire_monitor(False, seed, self._auxs_values())
                donated_fn = (self._jit_fwd_eval_donated
                              if not stream else None)
                fwd = (self._stream_fns()["fwd_eval"] if stream
                       else self._jit_fwd_eval)
                with self._prof_scope("Executor::forward"):
                    _count_dispatch()
                    if donated_fn is not None:
                        vals = self._args_values()
                        donated = {n: vals.pop(n)
                                   for n in self._donated_names}
                        outs = _timed_dispatch(
                            donated_fn, donated, vals,
                            self._auxs_values(), seed)
                    else:
                        outs = _timed_dispatch(
                            fwd, self._args_values(), self._auxs_values(),
                            seed)
            if stream:
                jax.effects_barrier()   # flush in-flight tap callbacks
        finally:
            if stream:
                # analyze: ok(threads) documented debug-path limitation (_StreamTarget docstring); cleared in the finally
                _STREAM_TARGET.exe = None
        self._outputs = [NDArray(o, self._ctx) for o in outs]
        self._pending_train_fwd = False
        return self._outputs

    def backward(self, out_grads=None, is_train=True):
        if not self._diff_names:
            self._pending_train_fwd = False
            return
        n_out = len(self._output_names)
        if out_grads is None:
            ograds = [None] * n_out
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            ograds = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                      for g in out_grads]
        # reuse the seed/aux snapshot fixed at forward(is_train=True) so the
        # recomputed forward inside the fused program matches what the user
        # observed (and aux momentum updates apply exactly once per step)
        seed = self._train_seed if self._train_seed is not None \
            else self._next_seed()
        auxs = self._train_auxs if self._train_auxs is not None \
            else self._auxs_values()
        self._train_seed = None
        self._train_auxs = None
        monitored = self._monitor_active() and self._pending_train_fwd
        # MXNET_BACKWARD_DO_MIRROR rematerializes the forward inside the
        # fused fwd+bwd (jax.checkpoint) — the re-run would fire every
        # stream tap twice, so monitored mirror steps use the tapped
        # program instead (bind-time snapshot, matching _stream_fns)
        stream = (monitored and self._monitor_mode == "stream"
                  and not self._mirror)
        if monitored and not stream:
            # tapped mode: fire taps with the same seed/aux snapshot the
            # fused program will consume, so the monitored values match
            # what executes
            self._fire_monitor(True, seed, auxs)
        if stream:
            # analyze: ok(threads) documented debug-path limitation: the running executor is published globally for the duration of a monitored launch (_StreamTarget docstring)
            _STREAM_TARGET.exe = self
        try:
            fwd_bwd = (self._stream_fns()["fwd_bwd"] if stream
                       else self._jit_fwd_bwd)
            with self._prof_scope("Executor::forward_backward"):
                _count_dispatch()
                outs, new_auxs, grads = _timed_dispatch(
                    fwd_bwd, self._args_values(), auxs, seed, ograds)
            if stream:
                jax.effects_barrier()   # flush in-flight tap callbacks
        finally:
            if stream:
                # analyze: ok(threads) documented debug-path limitation (_StreamTarget docstring); cleared in the finally
                _STREAM_TARGET.exe = None
        self._outputs = [NDArray(o, self._ctx) for o in outs]
        self._pending_train_fwd = False
        self._write_auxs(new_auxs)
        for name, g in grads.items():
            req = self._grad_req.get(name, "null")
            dst = self.grad_dict.get(name)
            if dst is None or req == "null":
                continue
            g = g.astype(dst._data.dtype)
            if req == "add":
                dst._set_data(dst._data + g)
            else:
                dst._set_data(g)

    def _write_auxs(self, new_auxs):
        for name, v in new_auxs.items():
            self.aux_dict[name]._set_data(v)

    # ------------------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        # staging preserves each destination's placement: a param the
        # bind installed with a NamedSharding (mx.sharding annotations
        # resolved in _install_param_shardings) re-shards the incoming
        # host values instead of collapsing back to the single bind
        # device; unsharded params keep the exact old behavior (their
        # current sharding IS the ctx device).
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                dst = self.arg_dict[name]
                dst._set_data(
                    jax.device_put(arr._data, dst._data.sharding))
            elif not allow_extra_params:
                raise MXNetError("unknown arg '%s'" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    dst = self.aux_dict[name]
                    dst._set_data(
                        jax.device_put(arr._data, dst._data.sharding))
                elif not allow_extra_params:
                    raise MXNetError("unknown aux '%s'" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor bound with new data shapes; weights are
        shared (reference: GraphExecutor::Reshape, graph_executor.h:110).
        The jit cache keys on shape, so recompilation is automatic."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape_partial(**kwargs)
        new_args = {}
        for name, shp in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[name]
            if shp is not None and tuple(shp) != cur.shape:
                new_args[name] = nd_zeros(shp, self._ctx, cur.dtype)
            else:
                new_args[name] = cur
        grad_dict = {}
        for name, arr in new_args.items():
            if self._grad_req.get(name, "null") != "null":
                prev = self.grad_dict.get(name)
                if prev is not None and prev.shape == arr.shape:
                    grad_dict[name] = prev
                else:
                    grad_dict[name] = nd_zeros(arr.shape, self._ctx, arr.dtype)
        return Executor(self._symbol, self._ctx, new_args, grad_dict,
                        dict(self.aux_dict), dict(self._grad_req),
                        self._group2ctx)

    def debug_str(self):
        lines = ["Symbol outputs: %s" % ", ".join(self._output_names)]
        for node in self._symbol._topo():
            kind = "var" if node.is_var else node.op.name
            lines.append("  %s %s <- %s" % (kind, node.name,
                                            [n.name for n, _ in node.inputs]))
        return "\n".join(lines)

    # ------------------------------------------------------------------
    # binding entry points (invoked from Symbol)
    # ------------------------------------------------------------------
    @staticmethod
    def _normalize_grad_req(grad_req, arg_names):
        if isinstance(grad_req, str):
            return {n: grad_req for n in arg_names}
        if isinstance(grad_req, (list, tuple)):
            return dict(zip(arg_names, grad_req))
        if isinstance(grad_req, dict):
            return {n: grad_req.get(n, "null") for n in arg_names}
        raise MXNetError("invalid grad_req %r" % (grad_req,))

    @staticmethod
    def _simple_bind(symbol, ctx, grad_req, type_dict, group2ctx,
                     shared_exec, shared_buffer, shape_kwargs):
        ctx = ctx if ctx is not None else current_context()
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        arg_shapes, arg_types, aux_shapes, aux_types = \
            symbol.infer_shape_type(shape_kwargs, type_dict)
        if any(s is None for s in arg_shapes):
            missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
            raise MXNetError("simple_bind: cannot infer shapes of %s" % missing)

        grad_req_dict = Executor._normalize_grad_req(grad_req, arg_names)
        # data/label inputs default to grad null under 'write' like the
        # reference Module behavior is handled by the caller; here we follow
        # the grad_req given.
        arg_dict = {}
        for name, shp, dt in zip(arg_names, arg_shapes, arg_types):
            shared = shared_exec.arg_dict.get(name) if shared_exec else None
            if shared is not None and shared.shape == tuple(shp):
                arg_dict[name] = shared
            else:
                arg_dict[name] = nd_zeros(shp, ctx, type_dict.get(name, dt))
        grad_dict = {}
        for name in arg_names:
            if grad_req_dict.get(name, "null") != "null":
                arr = arg_dict[name]
                grad_dict[name] = nd_zeros(arr.shape, ctx, arr.dtype)
        aux_dict = {}
        for name, shp, dt in zip(aux_names, aux_shapes, aux_types):
            shared = shared_exec.aux_dict.get(name) if shared_exec else None
            if shared is not None and shared.shape == tuple(shp):
                aux_dict[name] = shared
            else:
                aux_dict[name] = nd_zeros(shp, ctx, dt)
        Executor._install_param_shardings(symbol, arg_dict, grad_dict,
                                          aux_dict)
        return Executor(symbol, ctx, arg_dict, grad_dict, aux_dict,
                        grad_req_dict, group2ctx)

    @staticmethod
    def _install_param_shardings(symbol, arg_dict, grad_dict, aux_dict):
        """Bind-time GSPMD placement: resolve ``__sharding__`` var attrs
        against the selected mesh (mx.sharding.set_mesh / MXTPU_MESH)
        and device_put each annotated parameter — and its grad buffer —
        with the resulting NamedSharding, so per-device param bytes
        shrink the moment the executor exists (the HBM census reads
        this).  No mesh selected, or no annotations: no-op."""
        from . import sharding as _sharding
        mesh = _sharding.get_mesh()
        if mesh is None:
            return
        specs = _sharding.collect_var_specs(symbol)
        if not specs:
            return
        placed = set()
        for name, s in specs.items():
            for store in (arg_dict, aux_dict):
                arr = store.get(name)
                if arr is None:
                    continue
                ns = _sharding.resolve(s, arr.shape, mesh, what=name)
                arr._set_data(jax.device_put(arr._data, ns))
                placed.add(name)
                g = grad_dict.get(name) if store is arg_dict else None
                if g is not None:
                    g._set_data(jax.device_put(g._data, ns))
        # every OTHER bound buffer goes replicated over the same mesh:
        # jit refuses argument sets committed to different device sets,
        # so once one param lives on the mesh, all of them (and the
        # inputs) must.  Module binds immediately re-place data/label
        # with P('dp') in executor_group._install_shardings; direct
        # simple_bind users (mx.decode under an mp mesh) keep the
        # replicated placement, which GSPMD treats as free.
        repl = _sharding.NamedSharding(mesh, _sharding.P())
        for store in (arg_dict, aux_dict):
            for name, arr in store.items():
                if name in placed:
                    continue
                arr._set_data(jax.device_put(arr._data, repl))
                g = grad_dict.get(name) if store is arg_dict else None
                if g is not None:
                    g._set_data(jax.device_put(g._data, repl))

    @staticmethod
    def _bind(symbol, ctx, args, args_grad, grad_req, aux_states, group2ctx,
              shared_exec):
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            arg_dict = dict(zip(arg_names, args))
        else:
            arg_dict = dict(args)
        missing = [n for n in arg_names if n not in arg_dict]
        if missing:
            raise MXNetError("bind: missing arguments %s" % missing)
        if isinstance(args_grad, (list, tuple)):
            grad_dict = dict(zip(arg_names, args_grad))
        elif args_grad is None:
            grad_dict = {}
        else:
            grad_dict = dict(args_grad)
        if isinstance(aux_states, (list, tuple)):
            aux_dict = dict(zip(aux_names, aux_states))
        elif aux_states is None:
            aux_dict = {}
        else:
            aux_dict = dict(aux_states)
        for n in aux_names:
            if n not in aux_dict:
                raise MXNetError("bind: missing aux state %s" % n)
        grad_req_dict = Executor._normalize_grad_req(grad_req, arg_names)
        for n in arg_names:
            if n not in grad_dict:
                grad_req_dict[n] = "null"
        return Executor(symbol, ctx, arg_dict, grad_dict, aux_dict,
                        grad_req_dict, group2ctx)


class _LazyOutputs(list):
    """Returned by forward(is_train=True); materializes on first access so
    Module's fwd+bwd fuses into one program when outputs aren't read early."""

    def __init__(self, executor):
        super().__init__()
        self._ex = executor

    def _force(self):
        outs = self._ex.outputs
        if not list.__len__(self):
            self.extend(outs)
        return outs

    def __getitem__(self, i):
        self._force()
        return super().__getitem__(i)

    def __iter__(self):
        self._force()
        return super().__iter__()

    def __len__(self):
        self._force()
        return super().__len__()
