"""Cache-aware request routing across decode replicas (docs/FLEET.md).

A paged-cache replica is not stateless: the prefix trie it has already
published makes SOME prompts nearly free (shared blocks skip prefill)
and others expensive.  Routing by least-loaded alone throws that state
away — two requests sharing a long system prompt land on different
replicas and each pays full prefill.  :class:`FleetRouter` routes by
PREFIX AFFINITY instead: each replica carries a host-side mirror of
the block chains routed to it, and a request goes to the replica with
the deepest block-aligned prefix match, discounted by cache occupancy
(depth × (1 − occupancy)) so a nearly-full cache does not keep
winning traffic it would have to evict its own trie to admit.

Two more behaviors make the router fleet-shaped rather than a toy
hash ring:

* **Session stickiness** — a ``session`` key maps to the replica that
  served it last (bounded LRU), because a conversation's whole history
  is in ONE replica's cache; moving it replays the entire prefix.
* **Drain-free membership** — ``add_replica`` AOT-warms the engine
  BEFORE it enters the ring (the joining replica's first request
  compiles nothing), ``remove_replica`` stops routing to the replica
  FIRST and then drains its in-flight work, so scale-down never fails
  a request that was already admitted.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict

from ..base import MXNetError
from ..telemetry import REGISTRY

__all__ = ["FleetRouter"]

ROUTED = REGISTRY.counter(
    "fleet_router_requests", "requests placed by the fleet router, "
    "labeled by `policy`")
STICKY_HITS = REGISTRY.counter(
    "fleet_router_sticky_hits", "requests routed by session "
    "stickiness (bypassing the scoring policy)")
AFFINITY_BLOCKS = REGISTRY.counter(
    "fleet_router_affinity_blocks", "prefix blocks the chosen replica "
    "already held at routing time (the replay work affinity skipped)")
REPLICAS = REGISTRY.gauge(
    "fleet_replicas", "decode replicas currently in the routing ring "
    "(draining replicas excluded)")

_POLICIES = ("affinity", "least_loaded")


class _MirrorTrie:
    """Host-side mirror of the block chains routed to one replica.

    Same chain structure as ``PagedKVCache``'s trie, but holding no
    blocks — only the router's BELIEF about what the replica cached.
    Bounded: past ``max_blocks`` nodes the oldest routed chain is
    dropped leaf-first, mirroring the cache's own eviction order, so a
    long-running router's belief decays the same way the replica's
    trie does."""

    def __init__(self, block_size, max_blocks):
        self.block_size = int(block_size)
        self.max_blocks = int(max_blocks)
        self._root = {}
        self._count = 0
        self._chains = OrderedDict()       # chain tuple -> True (FIFO)

    def _chain(self, tokens, n_blocks):
        bs = self.block_size
        return [tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
                for i in range(n_blocks)]

    def match(self, tokens):
        """Depth (in blocks) of the deepest mirrored chain matching
        ``tokens`` — capped like ``acquire_prefix`` at
        ``(len - 1) // block_size`` so the score mirrors what the
        replica can actually share."""
        depth = 0
        children = self._root
        for key in self._chain(tokens, (len(tokens) - 1)
                               // self.block_size):
            node = children.get(key)
            if node is None:
                break
            depth += 1
            children = node["children"]
        return depth

    def add(self, tokens):
        keys = self._chain(tokens, len(tokens) // self.block_size)
        if not keys:
            return
        children = self._root
        for key in keys:
            node = children.get(key)
            if node is None:
                node = {"children": {}}
                children[key] = node
                self._count += 1
            children = node["children"]
        self._chains[tuple(keys)] = True
        self._chains.move_to_end(tuple(keys))
        while self._count > self.max_blocks and self._chains:
            old, _ = self._chains.popitem(last=False)
            self._drop(old)

    def _drop(self, keys):
        """Remove one chain's leaf-only nodes (shared ancestors of a
        newer chain survive — they are still live belief)."""
        path = []
        children = self._root
        for key in keys:
            node = children.get(key)
            if node is None:
                break
            path.append((children, key, node))
            children = node["children"]
        for children, key, node in reversed(path):
            if node["children"]:
                break
            del children[key]
            self._count -= 1


class FleetRouter:
    """Prefix-affinity router over named :class:`DecodeEngine`
    replicas.  Thread-safe; every route decision happens under one
    lock plus dirty reads of each engine's scheduler depth (a stale
    load estimate costs placement quality, never correctness)."""

    def __init__(self, policy=None, sticky=None, trie_blocks=None,
                 block_size=None, max_sessions=4096):
        if policy is None:
            policy = os.environ.get("MXNET_FLEET_POLICY", "affinity")
        if policy not in _POLICIES:
            raise MXNetError("MXNET_FLEET_POLICY=%s; use %s"
                             % (policy, "|".join(_POLICIES)))
        if sticky is None:
            sticky = os.environ.get("MXNET_FLEET_STICKY",
                                    "1") not in ("0", "false")
        if trie_blocks is None:
            trie_blocks = int(os.environ.get("MXNET_FLEET_TRIE_BLOCKS",
                                             "4096"))
        self.policy = policy
        self.sticky = bool(sticky)
        self._trie_blocks = int(trie_blocks)
        self._block_size = block_size      # None: adopt 1st replica's
        self._lock = threading.RLock()
        self._replicas = OrderedDict()     # name -> record dict
        self._sessions = OrderedDict()     # session -> replica name
        self._max_sessions = int(max_sessions)

    # -- membership ----------------------------------------------------
    def add_replica(self, name, engine, manifest=None):
        """Enter ``engine`` into the routing ring as ``name``.

        Warmup happens BEFORE ring insertion: ``aot_warm`` replays the
        engine's manifest (or runs geometry warmup) while the replica
        is still invisible to ``route``, so the first routed request
        dispatches a cached program — 0 compiles, the drain-free
        scale-up contract.  Returns the number of programs warmed."""
        with self._lock:
            if name in self._replicas:
                raise MXNetError("fleet: replica %r already registered"
                                 % name)
        warmed = engine.aot_warm(manifest)
        bs = self._block_size or engine.cache.block_size
        if engine.cache.block_size != bs:
            raise MXNetError(
                "fleet: replica %r block_size=%d != fleet block_size=%d"
                " (affinity depths would not be comparable)"
                % (name, engine.cache.block_size, bs))
        with self._lock:
            self._block_size = bs
            self._replicas[name] = {
                "engine": engine,
                "trie": _MirrorTrie(bs, self._trie_blocks),
                "draining": False,
            }
            REPLICAS.set(sum(1 for r in self._replicas.values()
                             if not r["draining"]))
        return warmed

    def remove_replica(self, name, timeout=None):
        """Take ``name`` out of the ring: stop routing to it FIRST,
        then drain its in-flight and queued work, then drop it.
        Returns True when the drain completed inside ``timeout``; the
        replica is removed either way (a stuck drain is the caller's
        signal to stop the engine hard)."""
        with self._lock:
            rec = self._replicas.get(name)
            if rec is None:
                raise MXNetError("fleet: no replica %r" % name)
            rec["draining"] = True
            REPLICAS.set(sum(1 for r in self._replicas.values()
                             if not r["draining"]))
        drained = rec["engine"].drain(timeout=timeout)
        with self._lock:
            self._replicas.pop(name, None)
            self._sessions = OrderedDict(
                (s, n) for s, n in self._sessions.items() if n != name)
        return drained

    def replicas(self):
        with self._lock:
            return [n for n, r in self._replicas.items()
                    if not r["draining"]]

    # -- placement -----------------------------------------------------
    @staticmethod
    def _load(engine):
        # dirty read (no engine lock): len()/iteration under the GIL
        # never sees torn state, and a one-step-stale depth only skews
        # a tie-break
        sched = engine._sched
        return (sum(1 for s in sched.slots if s is not None)
                + len(sched.waiting))

    def route(self, tokens, session=None):
        """Place one prompt; returns ``(name, engine)`` and records
        the placement (mirror trie + session map)."""
        tokens = [int(t) for t in tokens]
        with self._lock:
            live = [(n, r) for n, r in self._replicas.items()
                    if not r["draining"]]
            if not live:
                raise MXNetError("fleet: no live replicas")
            name = None
            if self.sticky and session is not None:
                prev = self._sessions.get(session)
                if prev is not None and any(n == prev for n, _ in live):
                    name = prev
                    STICKY_HITS.inc()
            depth = 0
            if name is None:
                name, depth = self._pick(tokens, live)
            rec = self._replicas[name]
            rec["trie"].add(tokens)
            if session is not None:
                self._sessions[session] = name
                self._sessions.move_to_end(session)
                while len(self._sessions) > self._max_sessions:
                    self._sessions.popitem(last=False)
            ROUTED.labels(policy=self.policy).inc()
            if depth:
                AFFINITY_BLOCKS.inc(depth)
            return name, rec["engine"]

    def _pick(self, tokens, live):
        """Score the live ring.  ``affinity``: depth × (1 − occupancy),
        ties to the lighter replica; ``least_loaded``: scheduler depth
        only (the A/B baseline the fleet bench gates against)."""
        best, best_key, best_depth = None, None, 0
        for name, rec in live:
            eng = rec["engine"]
            load = self._load(eng)
            if self.policy == "least_loaded":
                key = (load, eng.cache.occupancy)
                depth = 0
            else:
                depth = rec["trie"].match(tokens)
                score = depth * (1.0 - eng.cache.occupancy)
                key = (-score, load, eng.cache.occupancy)
            if best_key is None or key < best_key:
                best, best_key, best_depth = name, key, depth
        return best, best_depth

    def submit(self, tokens, session=None, **kwargs):
        """Route + submit in one call; returns ``(name, handle)``."""
        name, engine = self.route(tokens, session=session)
        return name, engine.submit(tokens, **kwargs)

    # -- observability -------------------------------------------------
    def stats(self):
        with self._lock:
            return {
                "policy": self.policy,
                "sticky": self.sticky,
                "sessions": len(self._sessions),
                "replicas": {
                    n: {
                        "draining": r["draining"],
                        "load": self._load(r["engine"]),
                        "cache_occupancy":
                            round(r["engine"].cache.occupancy, 4),
                        "mirror_blocks": r["trie"]._count,
                    } for n, r in self._replicas.items()
                },
            }
