"""Tensor-parallel decode: mesh selection + witnesses (docs/FLEET.md).

The heavy lifting lives elsewhere — ``models.transformer`` annotates
the decode-step weights/caches when ``tensor_parallel=<axis>`` is set,
and the executor resolves those annotations at bind time — so this
module is deliberately thin: it validates the geometry EARLY (a head
count the axis does not divide fails here with a message naming the
config key, not deep inside GSPMD), selects the mesh, and exposes the
per-device cache-bytes witness the fleet bench gates on.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import sharding as _sharding

__all__ = ["tp_mesh", "make_tp_engine", "per_device_cache_bytes"]


def tp_mesh(size, axis="mp"):
    """Select (or adopt) a 1-D tensor-parallel mesh of ``size`` devices.

    Reuses the current mesh when it already carries ``axis`` at the
    requested size — calling this twice, or after an explicit
    ``sharding.set_mesh``, is idempotent.  Raises when a DIFFERENT
    ``axis`` extent is already selected: silently rebuilding the mesh
    under a live engine would retrace every program it compiled.
    """
    size = int(size)
    if size < 1:
        raise MXNetError("tp_mesh: size must be >= 1, got %d" % size)
    mesh = _sharding.get_mesh()
    if mesh is not None and axis in mesh.axis_names:
        have = int(mesh.shape[axis])
        if have != size:
            raise MXNetError(
                "tp_mesh: mesh already has %s=%d, asked for %d "
                "(clear_mesh() first — a live engine compiled against "
                "the old mesh would retrace)" % (axis, have, size))
        return mesh
    return _sharding.set_mesh({axis: size})


def _check_tp_geometry(model_config, size, axis):
    """Fail fast on axis-indivisible shapes, naming the config key."""
    heads = int(model_config.get("num_heads", 16))
    d_model = int(model_config.get("d_model", 2048))
    ffn = model_config.get("ffn_dim") or 4 * d_model
    for key, dim in (("num_heads", heads), ("ffn_dim", int(ffn))):
        if dim % size:
            raise MXNetError(
                "tensor-parallel decode needs %s %% %s == 0 "
                "(%s=%d, %s=%d)" % (key, axis, key, dim, axis, size))


def make_tp_engine(arg_params, model_config, tensor_parallel=None,
                   axis="mp", **engine_kwargs):
    """Build a :class:`~mxnet_tpu.decode.DecodeEngine` whose step
    program is sharded over a tensor-parallel mesh.

    ``tensor_parallel=N`` selects (or validates) an ``{axis: N}`` mesh
    and threads ``tensor_parallel=axis`` into the model config, which
    is ALL the engine needs — the decode-step symbols annotate
    QKV/proj/FFN weights column/row-wise and the paged KV caches
    head-wise, bind-time resolution places every buffer, and GSPMD
    propagation shards the step.  ``tensor_parallel=None`` (or 1)
    returns a plain single-device engine, so callers can keep one code
    path.  Remaining kwargs go to the engine untouched.
    """
    from ..decode import DecodeEngine

    if tensor_parallel is None or int(tensor_parallel) == 1:
        return DecodeEngine(arg_params, model_config, **engine_kwargs)
    size = int(tensor_parallel)
    _check_tp_geometry(model_config, size, axis)
    tp_mesh(size, axis=axis)
    cfg = dict(model_config, tensor_parallel=axis)
    return DecodeEngine(arg_params, cfg, **engine_kwargs)


def per_device_cache_bytes(engine, device=None):
    """Bytes of paged-KV-cache storage resident on one device — the
    fleet bench's TP witness: head-sharded caches put ~1/mp of the
    replicated footprint on each device, and a regression here means
    the cache annotations stopped resolving (the engine would still be
    CORRECT, just silently paying replicated memory)."""
    return _sharding.per_device_param_bytes(engine._cache_arrs,
                                            device=device)
