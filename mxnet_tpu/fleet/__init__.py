"""mx.fleet — disaggregated, cache-aware serving at pod scale.

Serving a pod is not one engine problem, it is three stacked placement
problems, and this package owns all three (docs/FLEET.md):

* **Tensor-parallel decode** (:mod:`.tp`) — one logical decode engine
  whose weights and paged KV cache are sharded head-wise over an
  ``mp`` mesh axis.  The engine itself does not change: the decode
  step symbols accept ``tensor_parallel=<axis>`` and annotate the
  attention/FFN weights and per-layer cache blocks with GSPMD
  shardings, so the ONE compiled launch per iteration becomes a
  multi-device program.  Greedy streams stay bit-identical to
  single-device decoding, dispatch/retrace witnesses are unchanged,
  and per-device cache bytes drop ~1/mp — which is the whole point:
  TP buys cache headroom, not just FLOPs.
* **Prefill/decode disaggregation** (:mod:`.handoff`) — prefill-heavy
  workers stream finished KV-cache blocks to decode workers over
  ``kvstore_tpu.dist.alltoall_bytes``, reusing the sharded-checkpoint
  slice format as the wire format (same bounds + CRC discipline, so a
  corrupt or mis-sliced payload is rejected, never silently decoded).
  Every exchange carries a bounded timeout: a dead prefill worker
  degrades the decode worker to LOCAL prefill (counter + flight note),
  it never hangs the serving loop.
* **Cache-aware routing** (:mod:`.router`) — a :class:`FleetRouter`
  places each /generate request on the replica whose prefix trie
  already holds the longest block-aligned prefix of the prompt,
  discounted by cache occupancy (a full cache that would evict its own
  trie to admit you is not an affinity win), with session stickiness
  and drain-free scale-up/down: a joining replica is AOT-warmed
  BEFORE it enters the ring (first request compiles nothing), a
  leaving replica stops receiving traffic first and drains in-flight
  work before removal.
"""
from __future__ import annotations

from .handoff import (handoff_exchange, export_prefix, inject_prefix,
                      pack_blocks, unpack_blocks)
from .router import FleetRouter
from .tp import make_tp_engine, per_device_cache_bytes, tp_mesh

__all__ = [
    "FleetRouter",
    "make_tp_engine",
    "tp_mesh",
    "per_device_cache_bytes",
    "pack_blocks",
    "unpack_blocks",
    "export_prefix",
    "inject_prefix",
    "handoff_exchange",
]
