"""Prefill/decode disaggregation: KV-block handoff (docs/FLEET.md).

Chunked prefill and token-by-token decode want opposite things from a
device — prefill is compute-bound over long spans, decode is
latency-bound over single rows — so a pod splits them: PREFILL workers
run prompts to completion and stream the finished cache blocks to
DECODE workers, which inject them into their own paged cache and serve
the stream with a prefix that was computed elsewhere.

Wire format: the sharded-checkpoint slice discipline
(``checkpoint.sharded``) reused verbatim — each cache tensor is walked
shard-by-shard into ``(bounds, slice)`` records with a chained CRC32,
a JSON header carries the token prefix + geometry, and the slices ride
one ``npz`` blob.  A decode worker therefore validates a payload the
exact same way a restore validates a checkpoint: geometry mismatch or
CRC failure REJECTS the payload (counter + flight note) and the
request falls back to local prefill — wrong-weights cache rows can
never be injected silently.

Failure is a first-class outcome everywhere: the exchange collective
carries a bounded timeout (``MXNET_FLEET_HANDOFF_TIMEOUT_MS``), and a
dead prefill worker degrades its decode peers to local prefill — the
serving loop never blocks on a corpse.
"""
from __future__ import annotations

import io
import json
import struct

import numpy as _np

from ..base import MXNetError
from ..checkpoint.sharded import _tensor_crc, _unique_slices
from ..telemetry import REGISTRY
from ..telemetry.flight import RECORDER

__all__ = ["pack_blocks", "unpack_blocks", "export_prefix",
           "inject_prefix", "handoff_exchange"]

_MAGIC = b"MXFB1"     # MXnet Fleet Blocks v1

BLOCKS_EXPORTED = REGISTRY.counter(
    "fleet_blocks_exported", "finished KV-cache blocks packed for "
    "prefill->decode handoff")
BLOCKS_INJECTED = REGISTRY.counter(
    "fleet_blocks_injected", "handed-off KV-cache blocks injected into "
    "a decode worker's paged cache")
HANDOFF_BYTES = REGISTRY.counter(
    "fleet_handoff_bytes", "bytes of packed cache blocks moved over "
    "the handoff collective", unit="bytes")
PREFILL_FALLBACKS = REGISTRY.counter(
    "fleet_prefill_fallbacks", "handoffs that degraded to local "
    "prefill, labeled by `reason` (timeout/geometry/crc/oom)")


def pack_blocks(tensors, tokens, n_rows, block_size):
    """Serialize finished cache blocks for the wire.

    ``tensors`` maps cache-array name -> gathered block rows (the
    ``(n_blocks, block_size, H, D)`` slab for that layer); ``tokens``
    is the token prefix those rows encode (``len(tokens) == n_rows``).
    Slices + CRCs follow ``checkpoint.sharded`` exactly.
    """
    slices, index, n = {}, {}, 0
    for key in sorted(tensors):
        data = tensors[key]
        data = getattr(data, "_data", data)
        recs = []
        for bounds, arr in _unique_slices(data):
            skey = "s%d" % n
            n += 1
            slices[skey] = arr
            recs.append({"key": skey,
                         "lo": [int(b[0]) for b in bounds],
                         "hi": [int(b[1]) for b in bounds]})
        index[key] = {
            "shape": [int(s) for s in getattr(data, "shape", ())],
            "dtype": str(_np.dtype(getattr(data, "dtype", "float32"))),
            "slices": recs,
            "crc32": _tensor_crc(recs, slices),
        }
    blob = io.BytesIO()
    _np.savez(blob, **slices)
    header = json.dumps({
        "tokens": [int(t) for t in tokens],
        "n_rows": int(n_rows),
        "block_size": int(block_size),
        "tensors": index,
    }).encode()
    return (_MAGIC + struct.pack(">I", len(header)) + header
            + blob.getvalue())


def unpack_blocks(payload):
    """Parse + validate a :func:`pack_blocks` payload.  Returns
    ``(tensors, header)`` with every tensor reassembled from its slices
    and CRC-verified; raises ``MXNetError`` on any mismatch."""
    if not isinstance(payload, (bytes, bytearray)) \
            or payload[:len(_MAGIC)] != _MAGIC:
        raise MXNetError("handoff payload: bad magic (not a packed "
                         "cache-block frame)")
    off = len(_MAGIC)
    (hlen,) = struct.unpack(">I", bytes(payload[off:off + 4]))
    off += 4
    try:
        header = json.loads(bytes(payload[off:off + hlen]))
    except ValueError as e:
        raise MXNetError("handoff payload: unreadable header: %s" % e)
    off += hlen
    try:
        with _np.load(io.BytesIO(bytes(payload[off:]))) as npz:
            slices = {k: npz[k] for k in npz.files}
    except Exception as e:
        raise MXNetError("handoff payload: unreadable slice blob: %s"
                         % e)
    tensors = {}
    for key, rec in header.get("tensors", {}).items():
        if _tensor_crc(rec["slices"], slices) != rec["crc32"]:
            raise MXNetError("handoff payload: tensor %r failed CRC "
                             "validation" % key)
        out = _np.zeros(tuple(rec["shape"]), dtype=rec["dtype"])
        for r in rec["slices"]:
            sel = tuple(slice(lo, hi) for lo, hi in zip(r["lo"],
                                                        r["hi"]))
            out[sel] = slices[r["key"]]
        tensors[key] = out
    return tensors, header


def export_prefix(engine, tokens):
    """Pack the cache blocks a prefill engine holds for ``tokens``.

    Matches the prompt against the engine's published prefix trie
    (``acquire_prefix`` pins the blocks against eviction while their
    rows are read), gathers the per-layer rows, and returns the wire
    payload — or ``None`` when no full block of the prompt is cached,
    which the caller treats as nothing-to-hand-off.  The device read
    holds the engine's step lock: cache buffers are DONATED to the
    step program, so an unlocked read could touch an invalidated
    buffer mid-iteration.
    """
    blocks, n_rows = engine.cache.acquire_prefix(
        [int(t) for t in tokens])
    if not blocks:
        return None
    try:
        # analyze: ok(hostsync) host-side block-id list, never a device value
        idx = _np.asarray(blocks, _np.int32)
        tensors = {}
        with engine._step_lock:
            for name, nd in zip(engine._cache_names,
                                engine._cache_arrs):
                # analyze: ok(hostsync) the gather IS the handoff — exported rows must reach the host to go on the wire; off the step path, once per handoff
                tensors[name] = _np.asarray(nd._data[idx])
    finally:
        engine.cache.free(blocks)     # undo acquire_prefix's pin
    payload = pack_blocks(tensors, tokens[:n_rows], n_rows,
                          engine.cache.block_size)
    BLOCKS_EXPORTED.inc(len(blocks))
    HANDOFF_BYTES.inc(len(payload))
    return payload


def inject_prefix(engine, payload):
    """Install a handed-off payload into ``engine``'s paged cache and
    publish it in the prefix trie.  Returns the rows injected, or 0
    when the payload is rejected (geometry/CRC mismatch) or the cache
    cannot spare the blocks — both degrade to local prefill, counted
    under ``fleet_prefill_fallbacks``."""
    from ..decode.cache import CacheOOMError

    try:
        tensors, header = unpack_blocks(payload)
    except MXNetError as e:
        PREFILL_FALLBACKS.labels(reason="crc").inc()
        RECORDER.note("fleet_handoff_reject", error=str(e)[:200])
        return 0
    if header.get("block_size") != engine.cache.block_size \
            or set(tensors) != set(engine._cache_names) \
            or any(tuple(tensors[n].shape[1:])
                   != tuple(nd._data.shape[1:])
                   for n, nd in zip(engine._cache_names,
                                    engine._cache_arrs)):
        PREFILL_FALLBACKS.labels(reason="geometry").inc()
        RECORDER.note("fleet_handoff_reject",
                      error="cache geometry mismatch")
        return 0
    n_rows = int(header["n_rows"])
    n_blocks = n_rows // engine.cache.block_size
    with engine._step_lock:
        try:
            blocks = engine.cache.alloc(n_blocks)
        except CacheOOMError:
            PREFILL_FALLBACKS.labels(reason="oom").inc()
            return 0
        # analyze: ok(hostsync) host-side block-id list, never a device value
        idx = _np.asarray(blocks, _np.int32)
        for name, nd in zip(engine._cache_names, engine._cache_arrs):
            rows = tensors[name].astype(nd._data.dtype, copy=False)
            upd = nd._data.at[idx].set(rows)
            nd._set_data(upd)
        engine.cache.register_prefix(header["tokens"], n_rows, blocks)
    engine.cache.free(blocks)         # the trie keeps its reference
    BLOCKS_INJECTED.inc(n_blocks)
    return n_rows


def handoff_exchange(outbox, timeout_ms=None):
    """One all-to-all round of cache-block payloads across the world.

    ``outbox`` holds one payload (``bytes``, possibly empty) per rank;
    returns the received list, or ``None`` when the collective fails —
    most importantly on TIMEOUT, the shape a dead prefill worker takes.
    Callers treat ``None`` as degrade-to-local-prefill; they must
    never retry in a loop (the next request simply prefills locally
    while the pod heals).
    """
    import os

    from ..kvstore_tpu import dist as _dist

    if timeout_ms is None:
        timeout_ms = int(os.environ.get(
            "MXNET_FLEET_HANDOFF_TIMEOUT_MS", "10000"))
    try:
        return _dist.alltoall_bytes("fleet/handoff", outbox,
                                    timeout_ms=timeout_ms)
    except Exception as e:   # noqa: BLE001 — jax runtime raises its own types on timeout
        PREFILL_FALLBACKS.labels(reason="timeout").inc()
        RECORDER.note("fleet_handoff_timeout", error=str(e)[:200])
        return None
