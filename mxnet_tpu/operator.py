"""CustomOp: user-defined operators in Python.

Reference parity: python/mxnet/operator.py:426-472 (CustomOp /
CustomOpProp / register) + src/operator/custom/custom.cc. The reference
trampolines through C callbacks into Python from the engine; the
TPU-native realization is ``jax.pure_callback`` (host callback embedded
in the XLA program) wrapped in ``jax.custom_vjp`` so the user's
``backward`` drives autodiff (see ops/custom.py for the op itself). A
Custom op therefore works everywhere an ordinary op does — eager,
autograd.record, hybridized blocks, and bound executors — at the cost of
a host round-trip per call (the same cost the reference pays crossing
the C/Python boundary).

Usage (identical to the reference)::

    class Sigmoid(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            y = 1.0 / (1.0 + mx.nd.exp(-in_data[0]))
            self.assign(out_data[0], req[0], y)
        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            y = out_data[0]
            self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))

    @mx.operator.register("sigmoid")
    class SigmoidProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)
        def list_arguments(self): return ['data']
        def list_outputs(self): return ['output']
        def infer_shape(self, in_shape): return in_shape, [in_shape[0]], []
        def create_operator(self, ctx, shapes, dtypes): return Sigmoid()

    y = mx.nd.Custom(x, op_type='sigmoid')
    s = mx.sym.Custom(data=mx.sym.Variable('d'), op_type='sigmoid')
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register",
           "get_all_registered_operators"]

_PROP_REGISTRY = {}


class CustomOp:
    """Base class for custom operator implementations (reference
    operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad_req (reference
        CustomOp.assign: null/write/inplace/add)."""
        if req in ("null", None):
            return
        import jax.numpy as jnp
        from .ndarray.ndarray import NDArray
        val = src._data if isinstance(src, NDArray) else jnp.asarray(src)
        if req == "add":
            dst._set_data(dst._data + val)
        else:  # write / inplace
            dst._set_data(val)


class CustomOpProp:
    """Operator properties: shapes, types, and operator creation
    (reference operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def infer_storage_type(self, in_stype):
        return (in_stype, ["default"] * len(self.list_outputs()),
                ["default"] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Decorator registering a CustomOpProp under ``op_type=reg_name``
    (reference operator.py register :1101)."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _PROP_REGISTRY[reg_name] = prop_cls
        # drop cached instances of any previous registration under this
        # name (notebook/test re-registration must take effect) — both the
        # prop instances and the jitted Custom callables that close over
        # them
        for key in [k for k in _PROP_CACHE if k[0] == reg_name]:
            del _PROP_CACHE[key]
        from .ndarray import dispatch as _dispatch
        stale = [k for k in _dispatch._JIT_CACHE
                 if k[0] == "Custom" and ("op_type", reg_name) in k[1]]
        for key in stale:
            del _dispatch._JIT_CACHE[key]
        return prop_cls

    return deco


def get_all_registered_operators():
    return list(_PROP_REGISTRY)


_PROP_CACHE = {}
_PROP_CACHE_MAX = 256


def _make_prop(attrs):
    """Instantiate (with memoization — each nd.Custom call consults this
    from out_count, kw ordering, and the op body) the prop registered
    under attrs['op_type']. Props should treat infer_shape/infer_type as
    pure: the instance is shared across calls with equal attrs (the
    reference constructs one prop per op creation; per-call state belongs
    in create_operator's CustomOp)."""
    op_type = attrs.get("op_type")
    if op_type is None:
        raise MXNetError("Custom op requires op_type=")
    if op_type not in _PROP_REGISTRY:
        raise MXNetError("custom op '%s' is not registered "
                         "(mx.operator.register)" % op_type)
    kwargs = {k: str(v) for k, v in attrs.items() if k != "op_type"}
    key = (op_type, tuple(sorted(kwargs.items())))
    prop = _PROP_CACHE.get(key)
    if prop is None:
        prop = _PROP_REGISTRY[op_type](**kwargs)
        if len(_PROP_CACHE) >= _PROP_CACHE_MAX:
            _PROP_CACHE.clear()
        _PROP_CACHE[key] = prop
    return prop
