"""Autograd: imperative differentiation over recorded op tapes.

Reference parity: python/mxnet/autograd.py + src/imperative/imperative.cc
(RecordOp :183, Backward :270). The reference tags NDArrays with nnvm graph
nodes and runs nnvm::pass::Gradient; here the tape of eager ops is replayed
as a pure JAX function and differentiated with ``jax.vjp`` — one XLA
computation for the whole backward, rather than per-op backward kernels.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops import registry as _reg

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "get_symbol"]


class _AGState(threading.local):
    def __init__(self):
        super().__init__()
        self.recording = False
        self.training = False
        self.tape = []


_state = _AGState()


def is_recording():
    return _state.recording


def is_training():
    return _state.training


class _Scope:
    def __init__(self, recording=None, training=None):
        self._rec, self._train = recording, training

    def __enter__(self):
        self._saved = (_state.recording, _state.training)
        if self._rec is not None:
            _state.recording = self._rec
        if self._train is not None:
            _state.training = self._train
        return self

    def __exit__(self, *a):
        _state.recording, _state.training = self._saved


def record(train_mode=True):
    return _Scope(recording=True, training=train_mode)


def pause(train_mode=False):
    return _Scope(recording=False, training=train_mode)


def train_mode():
    return _Scope(training=True)


def predict_mode():
    return _Scope(training=False)


class _TapeRecord:
    __slots__ = ("opdef", "attrs", "is_train", "rng", "inputs", "outputs",
                 "custom")

    def __init__(self, opdef, attrs, is_train, rng, inputs, outputs,
                 custom=None):
        self.opdef = opdef
        self.attrs = attrs
        self.is_train = is_train
        self.rng = rng
        self.inputs = inputs     # list of NDArray or None
        self.outputs = outputs   # list of NDArray (visible outputs)
        self.custom = custom     # optional callable(*arrays)->arrays (Function)


def _record_op(opdef, attrs, is_train, rng, inputs, outputs, custom=None):
    rec = _TapeRecord(opdef, attrs, is_train, rng, inputs, outputs, custom)
    idx = len(_state.tape)
    _state.tape.append(rec)
    for o in outputs:
        o._autograd_entry = idx


def mark_variables(variables, gradients, grad_reqs="write"):
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req


def _replay_records(nodes, env, skip_ids, heads):
    """Replay tape records under an id→value environment; inputs absent
    from env are captured as stop_gradient constants. Outputs whose id is
    in ``skip_ids`` keep their env value (marked-leaf semantics). Returns
    the head values. Shared by backward() and the create_graph path so
    replay semantics cannot diverge."""
    def val(nd):
        if nd is None:
            return None
        got = env.get(id(nd))
        return got if got is not None else jax.lax.stop_gradient(nd._data)

    for rec in nodes:
        ins = [val(x) for x in rec.inputs]
        if rec.custom is not None:
            raw = rec.custom(*ins)
        else:
            with _reg._OpCtxScope(rec.is_train, rec.rng):
                raw = rec.opdef.fn(*ins, **rec.attrs)
        outs = list(raw) if isinstance(raw, (tuple, list)) else [raw]
        for o_nd, v in zip(rec.outputs, outs):
            if id(o_nd) not in skip_ids:
                env[id(o_nd)] = v
    res = []
    for o in heads:
        got = env.get(id(o))
        res.append(got if got is not None else o._data)
    return res


def _collect_subgraph(outputs):
    """Topo-ordered tape records reachable from outputs + leaf variables."""
    tape = _state.tape
    needed = set()
    stack = [o._autograd_entry for o in outputs if o._autograd_entry is not None]
    while stack:
        idx = stack.pop()
        if idx in needed:
            continue
        needed.add(idx)
        for inp in tape[idx].inputs:
            if inp is not None and inp._autograd_entry is not None:
                stack.append(inp._autograd_entry)
    order = sorted(needed)
    leaves = []
    seen = set()
    for idx in order:
        for inp in tape[idx].inputs:
            if (inp is not None and inp._grad_req != "null"
                    and id(inp) not in seen):
                seen.add(id(inp))
                leaves.append(inp)
    # marked outputs themselves can be leaves (x.attach_grad(); y=f(x))
    return [tape[i] for i in order], leaves


def backward(outputs, out_grads=None, retain_graph=False, train_mode=True,
             variables=None):
    """Compute gradients of outputs w.r.t. marked variables and write them
    into ``var.grad`` honoring grad_req (write/add)."""
    from .ndarray.ndarray import NDArray

    if isinstance(outputs, NDArray):
        outputs = [outputs]
    nodes, leaves = _collect_subgraph(outputs)
    explicit = variables is not None
    if explicit:
        leaves = list(variables)
    if not leaves:
        raise MXNetError("backward: no variables with grad attached "
                         "(call attach_grad/mark_variables first)")

    leaf_ids = [id(v) for v in leaves]
    leaf_id_set = set(leaf_ids)

    def _floatable(x):
        # int leaves/outputs flow float32 gradients (jax would emit
        # float0). Documented bound: int values above 2^24 lose
        # precision in the replayed forward, and fractional gradients
        # truncate on the cast back to the leaf dtype.
        return not jnp.issubdtype(x.dtype, jnp.inexact)

    def replay(leaf_vals):
        # a marked variable that is itself a record output stays a
        # leaf: keep the vjp input value so its gradient flows
        env = dict(zip(leaf_ids, leaf_vals))
        outs = _replay_records(nodes, env, leaf_id_set, outputs)
        # integer outputs would yield float0 cotangents (jax refuses int
        # differentials); the reference treats dtype as incidental —
        # d(x[idx])/dx is a scatter whatever the dtype — so grads flow
        # in float and are cast back to the leaf dtype at the end
        return [o.astype(jnp.float32) if _floatable(o) else o
                for o in outs]

    leaf_vals = [v._data for v in leaves]
    leaf_vals = [lv.astype(jnp.float32) if _floatable(lv) else lv
                 for lv in leaf_vals]
    with _Scope(recording=False, training=train_mode):
        out_vals, vjp_fn = jax.vjp(replay, leaf_vals)
    if out_grads is None:
        cts = [jnp.ones_like(v) for v in out_vals]
    else:
        cts = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
               for g in out_grads]
        cts = [c.astype(v.dtype) for c, v in zip(cts, out_vals)]
    (grads,) = vjp_fn(cts)

    if not retain_graph:
        _clear_tape()

    result = []
    for v, g in zip(leaves, grads):
        g = g.astype(v._data.dtype)
        if explicit:
            result.append(NDArray(g, v._ctx))
        elif v._grad_req == "add" and v._grad is not None:
            v._grad._set_data(v._grad._data + g)
        elif v._grad is not None:
            v._grad._set_data(g)
    return result if explicit else None


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients instead of writing .grad (parity: autograd.grad,
    python/mxnet/autograd.py:270-307 including ``create_graph=True``).

    With ``create_graph=True`` the returned gradients are themselves
    recorded: the whole first-order computation (replay + ``jax.vjp``)
    is re-entered as one custom tape op whose inputs are every external
    input of the differentiated subgraph, so a later ``backward`` through
    the returned gradients nests a second ``jax.vjp`` around the first —
    gradient-of-gradient, including paths through inputs that were *not*
    in ``variables`` (needed for gradient penalties, where the penalty is
    d loss/d x but the training gradient is w.r.t. the weights)."""
    from .ndarray.ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
    if create_graph:
        return _grad_create_graph(heads, variables, head_grads, train_mode)
    retain = retain_graph if retain_graph is not None else create_graph
    return backward(heads, out_grads=head_grads, retain_graph=retain,
                    train_mode=train_mode, variables=variables)


def _grad_create_graph(heads, variables, head_grads, train_mode):
    """First-order grads that stay on the tape (nested-vjp higher order)."""
    from .ndarray.ndarray import NDArray

    nodes, _ = _collect_subgraph(heads)
    for rec in nodes:
        if rec.custom is not None and getattr(rec.custom, "_mx_function",
                                              False):
            raise MXNetError(
                "create_graph=True through an autograd.Function is not "
                "supported: Function.backward closes over concrete forward "
                "state, so differentiating the returned gradient again "
                "would silently treat that state as constant. Express the "
                "op with recorded NDArray ops (or jax.custom_jvp) instead.")
    var_ids = [id(v) for v in variables]
    var_id_set = set(var_ids)

    # External inputs of the subgraph: every record input not produced by
    # an earlier record, variables first (a marked variable that is itself
    # a record output stays a leaf, mirroring backward()).
    produced = set()
    for rec in nodes:
        for o in rec.outputs:
            if id(o) not in var_id_set:
                produced.add(id(o))
    ext = list(variables)
    ext_ids = set(var_ids)
    for rec in nodes:
        for inp in rec.inputs:
            if (inp is not None and id(inp) not in produced
                    and id(inp) not in ext_ids):
                ext_ids.add(id(inp))
                ext.append(inp)

    # Head gradients that are NDArrays become ext inputs too: a recorded
    # head_grad (e.g. itself a function of x) must contribute to the
    # second-order gradient, not be frozen as a constant.
    if head_grads is None:
        hg_list = None
    else:
        hg_list = list(head_grads) if isinstance(head_grads, (list, tuple)) \
            else [head_grads]
        for g in hg_list:
            if isinstance(g, NDArray) and id(g) not in ext_ids:
                ext_ids.add(id(g))
                ext.append(g)
    ext_id_list = [id(x) for x in ext]

    def g_fn(*ext_vals):
        ext_env = dict(zip(ext_id_list, ext_vals))

        def run(var_vals):
            env = dict(ext_env)
            env.update(zip(var_ids, var_vals))
            return _replay_records(nodes, env, var_id_set, heads)

        var_vals = [ext_env[i] for i in var_ids]
        with _Scope(recording=False, training=train_mode):
            out_vals, vjp_fn = jax.vjp(run, var_vals)
            if hg_list is None:
                cts = [jnp.ones_like(v) for v in out_vals]
            else:
                cts = [ext_env[id(g)] if isinstance(g, NDArray)
                       else jnp.asarray(g) for g in hg_list]
            (gvals,) = vjp_fn(cts)
        return tuple(g.astype(v._data.dtype)
                     for v, g in zip(variables, gvals))

    ext_vals = [x._data for x in ext]
    gvals = g_fn(*ext_vals)
    grads = [NDArray(g, v._ctx) for v, g in zip(variables, gvals)]
    if is_recording():
        _record_op(None, {}, is_training(), None, list(ext), grads,
                   custom=g_fn)
    return grads


def _clear_tape():
    for rec in _state.tape:
        for o in rec.outputs:
            o._autograd_entry = None
    _state.tape.clear()


def get_symbol(x):
    raise NotImplementedError("autograd.get_symbol: use symbolic API instead")


class Function:
    """User-defined differentiable function (parity: autograd.Function,
    python/mxnet/autograd.py:363). Subclass and implement forward/backward
    with NDArray semantics; internally wrapped as a jax.custom_vjp."""

    def __init__(self):
        self._used = False

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        from .ndarray import dispatch as _dispatch
        ctx = inputs[0]._ctx if inputs else None
        self_ref = self

        @jax.custom_vjp
        def _f(*arrs):
            nds = [NDArray(a, ctx) for a in arrs]
            with _Scope(recording=False):
                outs = self_ref.forward(*nds)
            if isinstance(outs, NDArray):
                return outs._data
            return tuple(o._data for o in outs)

        def _fwd(*arrs):
            return _f(*arrs), None

        def _bwd(res, g):
            gs = (g,) if not isinstance(g, (tuple, list)) else tuple(g)
            gnds = [NDArray(x, ctx) for x in gs]
            with _Scope(recording=False):
                igrads = self_ref.backward(*gnds)
            if isinstance(igrads, NDArray):
                igrads = (igrads,)
            return tuple(x._data for x in igrads)

        _f.defvjp(_fwd, _bwd)
        _f._mx_function = True
        arrs = [x._data for x in inputs]
        raw = _f(*arrs)
        outs_raw = list(raw) if isinstance(raw, tuple) else [raw]
        outputs = [NDArray(o, ctx) for o in outs_raw]
        if is_recording():
            _record_op(None, {}, is_training(), None, list(inputs), outputs,
                       custom=_f)
        return outputs[0] if len(outputs) == 1 else outputs
