"""Autograd: imperative differentiation over recorded op tapes.

Reference parity: python/mxnet/autograd.py + src/imperative/imperative.cc
(RecordOp :183, Backward :270). The reference tags NDArrays with nnvm graph
nodes and runs nnvm::pass::Gradient; here the tape of eager ops is replayed
as a pure JAX function and differentiated with ``jax.vjp`` — one XLA
computation for the whole backward, rather than per-op backward kernels.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops import registry as _reg

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "get_symbol"]


class _AGState(threading.local):
    def __init__(self):
        super().__init__()
        self.recording = False
        self.training = False
        self.tape = []


_state = _AGState()


def is_recording():
    return _state.recording


def is_training():
    return _state.training


class _Scope:
    def __init__(self, recording=None, training=None):
        self._rec, self._train = recording, training

    def __enter__(self):
        self._saved = (_state.recording, _state.training)
        if self._rec is not None:
            _state.recording = self._rec
        if self._train is not None:
            _state.training = self._train
        return self

    def __exit__(self, *a):
        _state.recording, _state.training = self._saved


def record(train_mode=True):
    return _Scope(recording=True, training=train_mode)


def pause(train_mode=False):
    return _Scope(recording=False, training=train_mode)


def train_mode():
    return _Scope(training=True)


def predict_mode():
    return _Scope(training=False)


class _TapeRecord:
    __slots__ = ("opdef", "attrs", "is_train", "rng", "inputs", "outputs",
                 "custom")

    def __init__(self, opdef, attrs, is_train, rng, inputs, outputs,
                 custom=None):
        self.opdef = opdef
        self.attrs = attrs
        self.is_train = is_train
        self.rng = rng
        self.inputs = inputs     # list of NDArray or None
        self.outputs = outputs   # list of NDArray (visible outputs)
        self.custom = custom     # optional callable(*arrays)->arrays (Function)


def _record_op(opdef, attrs, is_train, rng, inputs, outputs, custom=None):
    rec = _TapeRecord(opdef, attrs, is_train, rng, inputs, outputs, custom)
    idx = len(_state.tape)
    _state.tape.append(rec)
    for o in outputs:
        o._autograd_entry = idx


def mark_variables(variables, gradients, grad_reqs="write"):
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req


def _collect_subgraph(outputs):
    """Topo-ordered tape records reachable from outputs + leaf variables."""
    tape = _state.tape
    needed = set()
    stack = [o._autograd_entry for o in outputs if o._autograd_entry is not None]
    while stack:
        idx = stack.pop()
        if idx in needed:
            continue
        needed.add(idx)
        for inp in tape[idx].inputs:
            if inp is not None and inp._autograd_entry is not None:
                stack.append(inp._autograd_entry)
    order = sorted(needed)
    leaves = []
    seen = set()
    for idx in order:
        for inp in tape[idx].inputs:
            if (inp is not None and inp._grad_req != "null"
                    and id(inp) not in seen):
                seen.add(id(inp))
                leaves.append(inp)
    # marked outputs themselves can be leaves (x.attach_grad(); y=f(x))
    return [tape[i] for i in order], leaves


def backward(outputs, out_grads=None, retain_graph=False, train_mode=True,
             variables=None):
    """Compute gradients of outputs w.r.t. marked variables and write them
    into ``var.grad`` honoring grad_req (write/add)."""
    from .ndarray.ndarray import NDArray

    if isinstance(outputs, NDArray):
        outputs = [outputs]
    nodes, leaves = _collect_subgraph(outputs)
    explicit = variables is not None
    if explicit:
        leaves = list(variables)
    if not leaves:
        raise MXNetError("backward: no variables with grad attached "
                         "(call attach_grad/mark_variables first)")

    leaf_ids = [id(v) for v in leaves]
    leaf_id_set = set(leaf_ids)

    def replay(leaf_vals):
        env = dict(zip(leaf_ids, leaf_vals))

        def val(nd):
            if nd is None:
                return None
            got = env.get(id(nd))
            return got if got is not None else jax.lax.stop_gradient(nd._data)

        for rec in nodes:
            ins = [val(x) for x in rec.inputs]
            if rec.custom is not None:
                raw = rec.custom(*ins)
            else:
                with _reg._OpCtxScope(rec.is_train, rec.rng):
                    raw = rec.opdef.fn(*ins, **rec.attrs)
            outs = list(raw) if isinstance(raw, (tuple, list)) else [raw]
            for o_nd, v in zip(rec.outputs, outs):
                # a marked variable that is itself a record output stays a
                # leaf: keep the vjp input value so its gradient flows
                if id(o_nd) not in leaf_id_set:
                    env[id(o_nd)] = v
        res = []
        for o in outputs:
            got = env.get(id(o))
            res.append(got if got is not None else o._data)
        return res

    leaf_vals = [v._data for v in leaves]
    with _Scope(recording=False, training=train_mode):
        out_vals, vjp_fn = jax.vjp(replay, leaf_vals)
    if out_grads is None:
        cts = [jnp.ones_like(v) for v in out_vals]
    else:
        cts = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
               for g in out_grads]
    (grads,) = vjp_fn(cts)

    if not retain_graph:
        _clear_tape()

    result = []
    for v, g in zip(leaves, grads):
        g = g.astype(v._data.dtype)
        if explicit:
            result.append(NDArray(g, v._ctx))
        elif v._grad_req == "add" and v._grad is not None:
            v._grad._set_data(v._grad._data + g)
        elif v._grad is not None:
            v._grad._set_data(g)
    return result if explicit else None


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients instead of writing .grad (parity: autograd.grad)."""
    from .ndarray.ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
    if create_graph:
        raise NotImplementedError("higher-order autograd.grad lands with the "
                                  "symbolic higher-order pass")
    retain = retain_graph if retain_graph is not None else create_graph
    return backward(heads, out_grads=head_grads, retain_graph=retain,
                    train_mode=train_mode, variables=variables)


def _clear_tape():
    for rec in _state.tape:
        for o in rec.outputs:
            o._autograd_entry = None
    _state.tape.clear()


def get_symbol(x):
    raise NotImplementedError("autograd.get_symbol: use symbolic API instead")


class Function:
    """User-defined differentiable function (parity: autograd.Function,
    python/mxnet/autograd.py:363). Subclass and implement forward/backward
    with NDArray semantics; internally wrapped as a jax.custom_vjp."""

    def __init__(self):
        self._used = False

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        from .ndarray import dispatch as _dispatch
        ctx = inputs[0]._ctx if inputs else None
        self_ref = self

        @jax.custom_vjp
        def _f(*arrs):
            nds = [NDArray(a, ctx) for a in arrs]
            with _Scope(recording=False):
                outs = self_ref.forward(*nds)
            if isinstance(outs, NDArray):
                return outs._data
            return tuple(o._data for o in outs)

        def _fwd(*arrs):
            return _f(*arrs), None

        def _bwd(res, g):
            gs = (g,) if not isinstance(g, (tuple, list)) else tuple(g)
            gnds = [NDArray(x, ctx) for x in gs]
            with _Scope(recording=False):
                igrads = self_ref.backward(*gnds)
            if isinstance(igrads, NDArray):
                igrads = (igrads,)
            return tuple(x._data for x in igrads)

        _f.defvjp(_fwd, _bwd)
        arrs = [x._data for x in inputs]
        raw = _f(*arrs)
        outs_raw = list(raw) if isinstance(raw, tuple) else [raw]
        outputs = [NDArray(o, ctx) for o in outs_raw]
        if is_recording():
            _record_op(None, {}, is_training(), None, list(inputs), outputs,
                       custom=_f)
        return outputs[0] if len(outputs) == 1 else outputs
