"""Block / HybridBlock / SymbolBlock.

Reference parity: python/mxnet/gluon/block.py (Block :126, HybridBlock
:669, ``hybridize`` → ``_build_cache`` → CachedOp :746-783, SymbolBlock).

TPU-native hybridization: instead of building an nnvm CachedOp, the block's
``hybrid_forward`` is traced under ``jax.jit`` with its NDArrays wrapping
tracers — the whole block becomes ONE XLA computation, cached per
(input shapes/dtypes, train-mode). Mutated non-differentiable parameters
(BatchNorm running stats) are threaded out of the traced function and
written back eagerly, keeping jit purity while preserving MXNet's in-place
aux-update semantics (FMutateInputs).
"""
from __future__ import annotations

import copy
import re
import threading

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray
from .. import autograd
from ..ops import registry as _reg
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope(threading.local):
    """Name manager for Block prefixes (reference block.py _BlockScope)."""
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                from ..base import current_name_manager
                prefix = current_name_manager().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *a):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class Block:
    """Base building block (reference gluon/block.py:126)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)" if self._children else "{name}()"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=_indent(str(block), 2))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(
                    value, type(existing)):
                raise TypeError("Changing attribute type for {name} from "
                                "{type1} to {type2} is not allowed.".format(
                                    name=name, type1=type(existing),
                                    type2=type(value)))
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All Parameters of this block and children, optionally filtered by
        a regex over names (reference block.py collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({n: p for n, p in self.params.items()
                        if pattern.match(n)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            from ..initializer import Uniform
            init = Uniform()
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    # ------------------------------------------------------------------
    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        from ..serialization import save_ndarray_file
        save_ndarray_file(filename, {k: v.data() for k, v in params.items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from ..serialization import load_ndarray_file
        loaded = load_ndarray_file(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise IOError("Parameter '%s' missing in '%s'"
                                  % (name, filename))
        for name, v in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise IOError("Parameter '%s' from '%s' not found in "
                                  "Block" % (name, filename))
                continue
            p = params[name]
            if p._data is None:
                p.shape = v.shape
                p.initialize(ctx=ctx)
            p.set_data(v)

    # legacy names (reference keeps both)
    save_params = save_parameters

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.load_parameters(filename, ctx, allow_missing, ignore_extra)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # ------------------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        summary = []

        def _hook(block, inp, out):
            outs = out if isinstance(out, (list, tuple)) else [out]
            n_params = sum(int(_np.prod(p.shape))
                           for p in block._reg_params.values()
                           if p.shape is not None)
            summary.append((block.name, type(block).__name__,
                            [tuple(o.shape) for o in outs
                             if isinstance(o, NDArray)], n_params))

        handles = []
        def _register(b):
            b._forward_hooks.append(_hook)
            handles.append(b)
        self.apply(_register)
        try:
            self(*inputs)
        finally:
            for b in handles:
                b._forward_hooks.remove(_hook)
        lines = ["%-30s %-20s %-25s %10s" % ("Layer", "Type", "Output Shape",
                                             "Params")]
        for name, typ, shapes, n in summary:
            lines.append("%-30s %-20s %-25s %10d"
                         % (name, typ, ",".join(map(str, shapes)), n))
        print("\n".join(lines))


def _indent(s, num):
    lines = s.split("\n")
    return ("\n" + " " * num).join(lines)


class HybridBlock(Block):
    """Block that can be compiled to one XLA computation
    (reference gluon/block.py:669)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_fns = {}
        self._flags = {}

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = kwargs
        self._cached_fns = {}
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._cached_fns = {}
        super().cast(dtype)

    def _infer_param_shapes(self, *args):
        """Per-layer rule completing unknown (0) parameter dims from the
        concrete inputs — the deferred-init analog of the reference's
        infer_shape pass (gluon/block.py _deferred_infer_shape)."""

    # ------------------------------------------------------------------
    def _collect_all_params(self):
        """(grad_params, aux_params) dicts keyed by parameter NAME, over
        this block and all children (what the traced fn takes as inputs)."""
        grad_p, aux_p = {}, {}

        def visit(block):
            for p in block._reg_params.values():
                (aux_p if p.grad_req == "null" else grad_p)[p.name] = p
            for c in block._children.values():
                visit(c)
        visit(self)
        return grad_p, aux_p

    def forward(self, *args):
        if self._active:
            # deferred params must exist before tracing; resolve them with
            # one eager pass (only happens on the very first call)
            if any(p._data is None for p in self.collect_params().values()):
                return self._eager_forward(*args)
            return self._call_cached(*args)
        return self._eager_forward(*args)

    def _eager_forward(self, *args):
        """Eager path. Deferred-init resolution happens leaf-locally: when a
        parameter read raises, the layer's _infer_param_shapes completes the
        unknown dims from the inputs and init finishes (the reference's
        _deferred_infer_shape flow, gluon/block.py)."""
        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._infer_param_shapes(*args)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            params = {k: p.data() for k, p in self._reg_params.items()}
        from .. import ndarray as F
        return self.hybrid_forward(F, *args, **params)

    # ------------------------------------------------------------------
    def _call_cached(self, *args):
        grad_p, aux_p = self._collect_all_params()
        grad_names = sorted(grad_p)
        aux_names = sorted(aux_p)
        in_arrs = [a._data for a in args]
        is_train = autograd.is_training()
        key = (tuple((a.shape, str(a.dtype)) for a in in_arrs), is_train,
               tuple(grad_names), tuple(aux_names))
        cached = self._cached_fns.get(key)
        if cached is None:
            cached = self._build_cache(args, grad_names, aux_names, is_train)
            self._cached_fns[key] = cached
        fn = cached

        grad_vals = [grad_p[n].data()._data for n in grad_names]
        aux_vals = [aux_p[n].data()._data for n in aux_names]
        from .. import random as _rand
        seed = _rand.next_seed()
        outs, new_aux = fn(grad_vals, aux_vals, in_arrs, seed)
        # write mutated aux (BatchNorm running stats) back eagerly
        if is_train:
            for n, v in zip(aux_names, new_aux):
                aux_p[n].data()._set_data(v)
        ctx = args[0]._ctx if args else current_context()
        out_nds = [NDArray(o, ctx) for o in outs]

        if autograd.is_recording():
            # tape entry: pure fn of (inputs + grad params); aux and seed
            # closed over so replay reproduces the same computation
            aux_c = list(aux_vals)
            n_in = len(in_arrs)

            def custom(*arrs):
                outs2, _ = fn(list(arrs[n_in:]), aux_c, list(arrs[:n_in]),
                              seed)
                return tuple(outs2)

            inputs = list(args) + [grad_p[n].data() for n in grad_names]
            autograd._record_op(None, {}, is_train, None, inputs, out_nds,
                                custom=custom)
        return out_nds[0] if len(out_nds) == 1 else out_nds

    def _build_cache(self, args, grad_names, aux_names, is_train):
        """jit the whole hybrid_forward; one XLA computation per shape/mode
        (the CachedOp analog, reference cached_op.cc)."""
        self_ref = self

        def run(grad_vals, aux_vals, in_vals, seed):
            rng = jax.random.key(seed)
            grad_nd = dict(zip(grad_names, (NDArray(v) for v in grad_vals)))
            aux_nd = dict(zip(aux_names, (NDArray(v) for v in aux_vals)))
            in_nd = [NDArray(v) for v in in_vals]
            with _reg._OpCtxScope(is_train, rng), \
                    autograd._Scope(recording=False, training=is_train):
                out = self_ref._hybrid_call(in_nd, grad_nd, aux_nd)
            outs = out if isinstance(out, (list, tuple)) else [out]
            new_aux = [aux_nd[n]._data for n in aux_names]
            return tuple(o._data for o in outs), new_aux

        # analyze: ok(retrace) CachedGraph compiles once per hybridize cache entry; gluon's own tests pin cache hits
        return jax.jit(run)

    def _hybrid_call(self, in_nd, grad_nd, aux_nd):
        """Run hybrid_forward recursively with param NDArrays drawn from the
        traced pools (children share the same pools via name lookup)."""
        pools = (grad_nd, aux_nd)
        return _run_with_pools(self, in_nd, pools)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------------------------
    def export(self, path, epoch=0):
        """Write symbol.json + params for the symbolic/Module/C-predict
        world (reference block.py export)."""
        from .. import symbol as sym_mod
        from ..serialization import save_ndarray_file
        grad_p, aux_p = self._collect_all_params()
        data_var = sym_mod.var("data")
        with _SymbolTraceScope():
            out = _run_symbolic(self, [data_var])
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        out.save("%s-symbol.json" % path)
        arrs = {}
        for n, p in grad_p.items():
            arrs["arg:" + n] = p.data()
        for n, p in aux_p.items():
            arrs["aux:" + n] = p.data()
        save_ndarray_file("%s-%04d.params" % (path, epoch), arrs)
        return out


class _SymbolTraceScope:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        pass


def _run_symbolic(block, sym_inputs):
    """Recursively evaluate hybrid_forward with F=symbol and parameter
    variables, producing the exported graph."""
    from .. import symbol as F
    params = {k: p.var() for k, p in block._reg_params.items()}
    orig_calls = {}

    # children must also run symbolically: monkey-free approach — call
    # hybrid_forward directly with symbolic children wrappers
    class _SymChild:
        def __init__(self, child):
            self._child = child

        def __call__(self, *xs):
            return _run_symbolic(self._child, list(xs))

    saved = {}
    for name, child in block._children.items():
        for attr, val in list(vars(block).items()):
            if val is child:
                saved[attr] = val
                object.__setattr__(block, attr, _SymChild(child))
    # Sequential-style children stored only in _children
    saved_children = block._children
    block._children = {k: _SymChild(v) if isinstance(v, Block) else v
                       for k, v in saved_children.items()}
    try:
        out = block.hybrid_forward(F, *sym_inputs, **params)
    finally:
        block._children = saved_children
        for attr, val in saved.items():
            object.__setattr__(block, attr, val)
    return out


def _run_with_pools(block, in_nd, pools):
    """Evaluate block.hybrid_forward eagerly-on-tracers, drawing every
    parameter value from the shared traced pools by name."""
    grad_nd, aux_nd = pools
    params = {}
    for attr, p in block._reg_params.items():
        pool = aux_nd if p.grad_req == "null" else grad_nd
        params[attr] = pool[p.name]

    saved = {}

    class _TracedChild:
        def __init__(self, child):
            self._child = child

        def __call__(self, *xs):
            return _run_with_pools(self._child, list(xs), pools)

        def __getattr__(self, item):
            return getattr(self._child, item)

    for name, child in list(block._children.items()):
        for attr, val in list(vars(block).items()):
            if val is child:
                saved[attr] = val
                object.__setattr__(block, attr, _TracedChild(child))
    # Sequential-style children stored only in _children
    saved_children = block._children
    block._children = {k: _TracedChild(v) if isinstance(v, (Block,))
                       else v for k, v in saved_children.items()}
    from .. import ndarray as F
    try:
        out = block.hybrid_forward(F, *in_nd, **params)
    finally:
        block._children = saved_children
        for attr, val in saved.items():
            object.__setattr__(block, attr, val)
    return out


class SymbolBlock(HybridBlock):
    """Wrap a Symbol + params into a Block (reference block.py SymbolBlock);
    the import path for `export`ed models."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=None)
        from ..symbol import Symbol, Group
        if isinstance(outputs, (list, tuple)):
            outputs = Group(list(outputs))
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._symbol = outputs
        self._input_names = [i.name for i in inputs]
        arg_names = outputs.list_arguments()
        aux_names = set(outputs.list_auxiliary_states())
        existing = dict(params.items()) if params is not None else {}
        # the graph's per-variable user attrs: lr/wd mults map onto the
        # typed Parameter fields; everything else (e.g. __sharding__)
        # is carried verbatim so re-export round-trips (test_attr_parity)
        var_attrs = outputs.attr_dict()
        _consumed = ("__shape__", "__dtype__", "__init__",
                     "__storage_type__", "__lr_mult__", "__wd_mult__",
                     "lr_mult", "wd_mult")
        for name in arg_names + list(aux_names):
            if name in self._input_names:
                continue
            if name in existing:
                self._params._params[name] = existing[name]
            else:
                a = var_attrs.get(name, {})
                self._params._params[name] = Parameter(
                    name, allow_deferred_init=True,
                    grad_req="null" if name in aux_names else "write",
                    lr_mult=float(a.get("__lr_mult__", 1.0)),
                    wd_mult=float(a.get("__wd_mult__", 1.0)),
                    attrs={k: v for k, v in a.items()
                           if k not in _consumed})
        self._graph_cache = {}

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from ..symbol import load as sym_load
        from ..serialization import load_ndarray_file
        sym = sym_load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        from ..symbol import var
        inputs = [var(n) for n in input_names]
        block = SymbolBlock(sym, inputs)
        if param_file is not None:
            loaded = load_ndarray_file(param_file)
            for k, v in loaded.items():
                name = k.split(":", 1)[-1]
                if name in block._params._params:
                    p = block._params._params[name]
                    p.shape = v.shape
                    p.initialize(ctx=ctx)
                    p.set_data(v)
        return block

    def forward(self, *args):
        from ..executor import _build_graph_fn
        is_train = autograd.is_training()
        key = (tuple((tuple(a.shape), str(a.dtype)) for a in args), is_train)
        fn = self._graph_cache.get(key)
        if fn is None:
            graph_fn = _build_graph_fn(self._symbol)

            # analyze: ok(retrace) graph_fn/is_train are part of the _graph_cache key computed two lines above; the capture cannot outlive its key
            def run(arg_vals, aux_vals, in_vals, seed):
                all_args = dict(arg_vals)
                all_args.update(dict(zip(self._input_names, in_vals)))
                outs, _ = graph_fn(all_args, aux_vals, seed, is_train)
                return tuple(outs)

            # analyze: ok(retrace) HybridBlock forward compiles per (input signature, is_train) by the hybridize contract; witnessed by test_gluon
            fn = jax.jit(run)
            self._graph_cache[key] = fn
        aux_names = set(self._symbol.list_auxiliary_states())
        arg_param_names = sorted(
            n for n in self._params.keys()
            if n not in aux_names and n not in self._input_names)
        arg_vals = {n: self._params[n].data()._data for n in arg_param_names}
        aux_vals = {n: p.data()._data for n, p in self._params.items()
                    if n in aux_names}
        from .. import random as _rand
        seed = _rand.next_seed()
        in_arrs = [a._data for a in args]
        outs = fn(arg_vals, aux_vals, in_arrs, seed)
        ctx = args[0]._ctx if args else current_context()
        out_nds = [NDArray(o, ctx) for o in outs]

        if autograd.is_recording():
            # tape entry mirroring HybridBlock._call_cached: replay is a pure
            # fn of (inputs + arg params); aux and seed closed over
            aux_c = dict(aux_vals)
            n_in = len(in_arrs)

            def custom(*arrs):
                return tuple(fn(dict(zip(arg_param_names, arrs[n_in:])),
                                aux_c, list(arrs[:n_in]), seed))

            inputs = list(args) + [self._params[n].data()
                                   for n in arg_param_names]
            autograd._record_op(None, {}, is_train, None, inputs, out_nds,
                                custom=custom)
        return out_nds[0] if len(out_nds) == 1 else out_nds
