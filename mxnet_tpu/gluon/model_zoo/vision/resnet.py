"""Gluon ResNet v1 (He et al. 1512.03385, post-activation) and v2
(He et al. 1603.05027, pre-activation).

API parity with ``python/mxnet/gluon/model_zoo/vision/resnet.py``.

CONTRACT CONSTRAINT: parameter names must match the reference checkpoints
(``resnetv10_stage1_conv0_weight``...) so ``tools/convert_params.py`` output
and the local pretrained store load without remapping.  Under gluon's naming
rules that pins only the *construction order* of parametered layers inside
each name scope — everything else here (the per-block conv/BN plan tables,
the shared residual stem builder, the generated factory aliases) is our own
derivation from the papers, not the reference's statement sequence.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2"]


# Per-block convolution plans: (out_channels, kernel, stride, pad, bias,
# in_channels).  Stride goes on the first 3x3 for basic blocks, on the 1x1
# (v1) or the 3x3 (v2) for bottlenecks — the paper's placement (and, for
# v1's biased 1x1 convs, the reference's quirk, which the checkpoint layout
# bakes in).  in_channels entries mirror the reference declarations exactly:
# a conv with known in_channels allocates (and seeds) its weight eagerly,
# so this column pins the RNG consumption order of seeded initialization —
# the committed logits fixture depends on it.
def _basic_plan(ch, stride, in_ch):
    return [(ch, 3, stride, 1, False, in_ch), (ch, 3, 1, 1, False, ch)]


def _bottleneck_v1_plan(ch, stride):
    return [(ch // 4, 1, stride, 0, True, 0),
            (ch // 4, 3, 1, 1, False, ch // 4),
            (ch, 1, 1, 0, True, 0)]


def _bottleneck_v2_plan(ch, stride):
    return [(ch // 4, 1, 1, 0, False, 0),
            (ch // 4, 3, stride, 1, False, ch // 4),
            (ch, 1, 1, 0, False, 0)]


def _conv(ch, kernel, stride, pad, bias, in_channels=0):
    return nn.Conv2D(ch, kernel_size=kernel, strides=stride, padding=pad,
                     use_bias=bias, in_channels=in_channels)


class _ResidualV1(HybridBlock):
    """Post-activation residual unit: relu(body(x) + shortcut(x)).

    ``body`` is conv→BN pairs with interior relus; ``shortcut`` is a strided
    1x1 conv + BN when the shape changes, else identity.
    """

    def __init__(self, plan, stride, downsample, in_channels, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        last = len(plan) - 1
        for i, (ch, k, s, p, bias, in_ch) in enumerate(plan):
            self.body.add(_conv(ch, k, s, p, bias, in_ch))
            self.body.add(nn.BatchNorm())
            if i != last:
                self.body.add(nn.Activation("relu"))
        if downsample:
            out_ch = plan[-1][0]
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(_conv(out_ch, 1, stride, 0, False,
                                      in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        shortcut = x if self.downsample is None else self.downsample(x)
        return F.Activation(self.body(x) + shortcut, act_type="relu")


class BasicBlockV1(_ResidualV1):
    """Two 3x3 convs (ResNet-18/34 unit)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(_basic_plan(channels, stride, in_channels),
                         stride, downsample, in_channels, **kwargs)


class BottleneckV1(_ResidualV1):
    """1x1 (strided) → 3x3 → 1x1 expand (ResNet-50/101/152 unit)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(_bottleneck_v1_plan(channels, stride), stride,
                         downsample, in_channels, **kwargs)


class _ResidualV2(HybridBlock):
    """Pre-activation residual unit: each conv is preceded by BN→relu, the
    shortcut projection (if any) taps the FIRST pre-activation output, and
    the sum is returned un-activated."""

    def __init__(self, plan, stride, downsample, in_channels, **kwargs):
        super().__init__(**kwargs)
        self._depth = len(plan)
        for i, (ch, k, s, p, _bias, in_ch) in enumerate(plan, start=1):
            setattr(self, f"bn{i}", nn.BatchNorm())
            setattr(self, f"conv{i}", _conv(ch, k, s, p, False, in_ch))
        if downsample:
            self.downsample = _conv(plan[-1][0], 1, stride, 0, False,
                                    in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        shortcut = x
        for i in range(1, self._depth + 1):
            x = getattr(self, f"bn{i}")(x)
            x = F.Activation(x, act_type="relu")
            if i == 1 and self.downsample is not None:
                shortcut = self.downsample(x)
            x = getattr(self, f"conv{i}")(x)
        return x + shortcut


class BasicBlockV2(_ResidualV2):
    """Pre-activation pair of 3x3 convs."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(_basic_plan(channels, stride, in_channels),
                         stride, downsample, in_channels, **kwargs)


class BottleneckV2(_ResidualV2):
    """Pre-activation bottleneck; the stride sits on the 3x3."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(_bottleneck_v2_plan(channels, stride), stride,
                         downsample, in_channels, **kwargs)


def _imagenet_stem(seq, first_channels, thumbnail):
    """7x7/2 conv + BN + relu + 3x3/2 maxpool, or a bare 3x3 for CIFAR-size
    inputs (``thumbnail=True``)."""
    if thumbnail:
        seq.add(_conv(first_channels, 3, 1, 1, False))
    else:
        seq.add(nn.Conv2D(first_channels, 7, 2, 3, use_bias=False))
        seq.add(nn.BatchNorm())
        seq.add(nn.Activation("relu"))
        seq.add(nn.MaxPool2D(3, 2, 1))


def _stage(block, n_units, channels, stride, index, in_channels):
    """One spatial stage: a strided/projecting unit then n-1 identity units."""
    seq = nn.HybridSequential(prefix=f"stage{index}_")
    with seq.name_scope():
        seq.add(block(channels, stride, channels != in_channels,
                      in_channels=in_channels, prefix=""))
        for _ in range(n_units - 1):
            seq.add(block(channels, 1, False, in_channels=channels, prefix=""))
    return seq


class ResNetV1(HybridBlock):
    """Post-activation ResNet: stem → 4 stages → global pool → classifier."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        if len(layers) != len(channels) - 1:
            raise ValueError("need one channel count per stage plus the stem")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            _imagenet_stem(self.features, channels[0], thumbnail)
            for i, n_units in enumerate(layers):
                self.features.add(_stage(block, n_units, channels[i + 1],
                                         1 if i == 0 else 2, i + 1,
                                         channels[i]))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class ResNetV2(HybridBlock):
    """Pre-activation ResNet: input-normalising BN → stem → stages → final
    BN+relu → global pool → classifier."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        if len(layers) != len(channels) - 1:
            raise ValueError("need one channel count per stage plus the stem")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            _imagenet_stem(self.features, channels[0], thumbnail)
            width = channels[0]
            for i, n_units in enumerate(layers):
                self.features.add(_stage(block, n_units, channels[i + 1],
                                         1 if i == 0 else 2, i + 1, width))
                width = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=width)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


resnet_spec = {18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
               34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
               50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
               101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
               152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048])}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [{"basic_block": BasicBlockV1,
                          "bottle_neck": BottleneckV1},
                         {"basic_block": BasicBlockV2,
                          "bottle_neck": BottleneckV2}]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    """Instantiate a ResNet by (version, depth).  ``pretrained=True`` loads
    ``resnet{N}_v{V}.params`` from the LOCAL model store (model_store.py;
    populate with tools/convert_params.py — no network egress)."""
    if num_layers not in resnet_spec:
        raise ValueError(f"Invalid number of layers: {num_layers}. "
                         f"Options are {sorted(resnet_spec)}")
    if version not in (1, 2):
        raise ValueError(f"Invalid resnet version: {version}.")
    block_kind, layers, channels = resnet_spec[num_layers]
    net_cls = resnet_net_versions[version - 1]
    block_cls = resnet_block_versions[version - 1][block_kind]
    net = net_cls(block_cls, layers, channels, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, f"resnet{num_layers}_v{version}",
                        root=root, ctx=ctx)
    return net


def _register_factories():
    for depth in sorted(resnet_spec):
        for version in (1, 2):
            name = f"resnet{depth}_v{version}"

            def _factory(version=version, depth=depth, **kwargs):
                return get_resnet(version, depth, **kwargs)
            _factory.__name__ = name
            _factory.__qualname__ = name
            _factory.__doc__ = f"ResNet-{depth} v{version} model."
            globals()[name] = _factory


_register_factories()
