"""Gluon SqueezeNet 1.0/1.1 (Iandola et al. 1602.07360; 1.1 is the
forum-released variant with the same accuracy at ~2.4x less compute).

API parity with ``python/mxnet/gluon/model_zoo/vision/squeezenet.py``.

CONTRACT CONSTRAINT: checkpoint parameter names pin the construction order
of parametered layers; the per-version plan tables below re-derive that
order from the paper's macro-architecture table.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]

_POOL = "pool"
# (stem_channels, stem_kernel, plan); plan entries are either _POOL or a
# fire module's (squeeze, expand1x1, expand3x3) widths.
_PLANS = {
    "1.0": (96, 7, [_POOL, (16, 64, 64), (16, 64, 64), (32, 128, 128),
                    _POOL, (32, 128, 128), (48, 192, 192), (48, 192, 192),
                    (64, 256, 256), _POOL, (64, 256, 256)]),
    "1.1": (64, 3, [_POOL, (16, 64, 64), (16, 64, 64),
                    _POOL, (32, 128, 128), (32, 128, 128),
                    _POOL, (48, 192, 192), (48, 192, 192),
                    (64, 256, 256), (64, 256, 256)]),
}


def _relu_conv(channels, kernel, padding=0):
    seq = nn.HybridSequential(prefix="")
    seq.add(nn.Conv2D(channels, kernel, padding=padding))
    seq.add(nn.Activation("relu"))
    return seq


class _FireExpand(HybridBlock):
    """The fire module's two parallel expand convs, channel-concatenated."""

    def __init__(self, expand1x1, expand3x3, **kwargs):
        super().__init__(**kwargs)
        self.p1 = _relu_conv(expand1x1, 1)
        self.p2 = _relu_conv(expand3x3, 3, 1)

    def hybrid_forward(self, F, x):
        return F.concat(self.p1(x), self.p2(x), dim=1)


def _fire(squeeze, expand1x1, expand3x3):
    seq = nn.HybridSequential(prefix="")
    seq.add(_relu_conv(squeeze, 1))
    seq.add(_FireExpand(expand1x1, expand3x3))
    return seq


class SqueezeNet(HybridBlock):
    """Strided stem conv, fire modules interleaved with ceil-mode maxpools
    per the version plan, then a 1x1-conv classifier head (no Dense)."""

    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        try:
            stem_ch, stem_k, plan = _PLANS[version]
        except KeyError:
            raise ValueError(f"Unsupported SqueezeNet version {version}: "
                             f"1.0 or 1.1 expected") from None
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(stem_ch, kernel_size=stem_k, strides=2))
            self.features.add(nn.Activation("relu"))
            for step in plan:
                if step is _POOL:
                    self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                                   ceil_mode=True))
                else:
                    self.features.add(_fire(*step))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, kernel_size=1))
            self.output.add(nn.Activation("relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_squeezenet(version, pretrained=False, ctx=None, root=None, **kwargs):
    net = SqueezeNet(version, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, f"squeezenet{version}", root=root, ctx=ctx)
    return net


def squeezenet1_0(**kwargs):
    """SqueezeNet 1.0 from the paper."""
    return get_squeezenet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    """SqueezeNet 1.1: same accuracy, ~2.4x cheaper."""
    return get_squeezenet("1.1", **kwargs)
