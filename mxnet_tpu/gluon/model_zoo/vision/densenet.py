"""Gluon DenseNet 121/161/169/201 (Huang et al. 1608.06993).

API parity with ``python/mxnet/gluon/model_zoo/vision/densenet.py``.

CONTRACT CONSTRAINT: checkpoint parameter names pin the construction order
of parametered layers; the composite-function builder below re-derives the
architecture (BN-relu-conv composite functions, dense concatenation,
half-width transitions) from the paper.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201"]

# depth -> (stem width, growth rate k, layers per dense block)
densenet_spec = {121: (64, 32, [6, 12, 24, 16]),
                 161: (96, 48, [6, 12, 36, 24]),
                 169: (64, 32, [6, 12, 32, 32]),
                 201: (64, 32, [6, 12, 48, 32])}


def _composite(seq, channels, kernel, padding=0):
    """The paper's composite function H: BN → relu → conv."""
    seq.add(nn.BatchNorm())
    seq.add(nn.Activation("relu"))
    seq.add(nn.Conv2D(channels, kernel_size=kernel, padding=padding,
                      use_bias=False))


class _DenseLayer(HybridBlock):
    """Bottlenecked composite (1x1 to bn_size*k, then 3x3 to k channels);
    output is the input with the k new feature maps concatenated."""

    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        _composite(self.body, bn_size * growth_rate, 1)
        _composite(self.body, growth_rate, 3, padding=1)
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def hybrid_forward(self, F, x):
        return F.concat(x, self.body(x), dim=1)


def _dense_stage(n_layers, bn_size, growth_rate, dropout, index):
    stage = nn.HybridSequential(prefix=f"stage{index}_")
    with stage.name_scope():
        for _ in range(n_layers):
            stage.add(_DenseLayer(growth_rate, bn_size, dropout))
    return stage


def _transition(channels):
    """Between dense blocks: composite 1x1 conv then 2x2 average pool."""
    seq = nn.HybridSequential(prefix="")
    _composite(seq, channels, 1)
    seq.add(nn.AvgPool2D(pool_size=2, strides=2))
    return seq


class DenseNet(HybridBlock):
    """7x7/2 stem → dense blocks with half-width transitions → BN-relu →
    7x7 average pool → linear classifier."""

    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(num_init_features, kernel_size=7,
                                        strides=2, padding=3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2, padding=1))
            width = num_init_features
            last = len(block_config) - 1
            for i, n_layers in enumerate(block_config):
                self.features.add(_dense_stage(n_layers, bn_size, growth_rate,
                                               dropout, i + 1))
                width += n_layers * growth_rate
                if i != last:
                    width //= 2
                    self.features.add(_transition(width))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.AvgPool2D(pool_size=7))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_densenet(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    net = DenseNet(*densenet_spec[num_layers], **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, f"densenet{num_layers}", root=root, ctx=ctx)
    return net


def _register_factories():
    for depth in sorted(densenet_spec):
        name = f"densenet{depth}"

        def _factory(depth=depth, **kwargs):
            return get_densenet(depth, **kwargs)
        _factory.__name__ = name
        _factory.__qualname__ = name
        _factory.__doc__ = f"DenseNet-{depth} model."
        globals()[name] = _factory


_register_factories()
