"""Gluon MobileNet v1 (Howard et al. 1704.04861, depthwise-separable convs)
and v2 (Sandler et al. 1801.04381, inverted residuals / linear bottlenecks).

API parity with ``python/mxnet/gluon/model_zoo/vision/mobilenet.py``.

CONTRACT CONSTRAINT: checkpoint parameter names pin the construction order
of parametered layers (conv→BN triplets, the v2 ``features_``/``output_``/
``pred_`` prefixes); the stage tables below re-derive the architectures
from the papers' layer tables.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["MobileNet", "MobileNetV2", "mobilenet1_0", "mobilenet0_75",
           "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0",
           "mobilenet_v2_0_75", "mobilenet_v2_0_5", "mobilenet_v2_0_25"]

# v1 paper table 1 as (pointwise_out, stride) per separable block; the
# depthwise width equals the previous block's output width.
_V1_BLOCKS = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
              (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
              (1024, 1)]

# v2 paper table 2 as (expansion t, out_channels, stride) per bottleneck,
# with each "n>1" row unrolled (stride applies to the first repeat).
_V2_BLOCKS = [(1, 16, 1),
              (6, 24, 2), (6, 24, 1),
              (6, 32, 2), (6, 32, 1), (6, 32, 1),
              (6, 64, 2), (6, 64, 1), (6, 64, 1), (6, 64, 1),
              (6, 96, 1), (6, 96, 1), (6, 96, 1),
              (6, 160, 2), (6, 160, 1), (6, 160, 1),
              (6, 320, 1)]


class _RELU6(HybridBlock):
    """clip(x, 0, 6) — v2's quantization-friendly activation."""

    def hybrid_forward(self, F, x):
        return F.clip(x, a_min=0, a_max=6)


def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False):
    """conv → BN → (relu|relu6); the building triplet for both versions."""
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm(scale=True))
    if active:
        out.add(_RELU6() if relu6 else nn.Activation("relu"))


def _add_separable(out, dw_width, pw_width, stride):
    """v1 separable block: 3x3 depthwise (one group per channel) then 1x1
    pointwise, each with BN+relu."""
    _add_conv(out, dw_width, kernel=3, stride=stride, pad=1,
              num_group=dw_width)
    _add_conv(out, pw_width)


class _LinearBottleneck(HybridBlock):
    """v2 inverted residual: 1x1 expand (xt, relu6) → 3x3 depthwise →
    1x1 project (linear); identity shortcut when shape-preserving."""

    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        mid = in_channels * t
        with self.name_scope():
            self.out = nn.HybridSequential()
            _add_conv(self.out, mid, relu6=True)
            _add_conv(self.out, mid, kernel=3, stride=stride, pad=1,
                      num_group=mid, relu6=True)
            _add_conv(self.out, channels, active=False, relu6=True)

    def hybrid_forward(self, F, x):
        y = self.out(x)
        return y + x if self.use_shortcut else y


class MobileNet(HybridBlock):
    """v1: strided 3x3 stem, 13 depthwise-separable blocks, global pool,
    Dense classifier.  ``multiplier`` scales every width."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        scale = lambda w: int(w * multiplier)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            with self.features.name_scope():
                _add_conv(self.features, channels=scale(32), kernel=3,
                          pad=1, stride=2)
                prev = 32
                for width, stride in _V1_BLOCKS:
                    _add_separable(self.features, scale(prev), scale(width),
                                   stride)
                    prev = width
                self.features.add(nn.GlobalAvgPool2D())
                self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class MobileNetV2(HybridBlock):
    """v2: relu6 stem, 17 linear bottlenecks, 1280-wide head conv, global
    pool, and a 1x1-conv classifier (``output_pred_`` in checkpoints)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        scale = lambda w: int(w * multiplier)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="features_")
            with self.features.name_scope():
                _add_conv(self.features, scale(32), kernel=3, stride=2,
                          pad=1, relu6=True)
                prev = 32
                for t, width, stride in _V2_BLOCKS:
                    self.features.add(_LinearBottleneck(
                        in_channels=scale(prev), channels=scale(width),
                        t=t, stride=stride))
                    prev = width
                head = scale(1280) if multiplier > 1.0 else 1280
                _add_conv(self.features, head, relu6=True)
                self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.HybridSequential(prefix="output_")
            with self.output.name_scope():
                self.output.add(
                    nn.Conv2D(classes, 1, use_bias=False, prefix="pred_"),
                    nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _store_suffix(multiplier):
    """Model-store spelling of the multiplier: '1.0'/'0.5' keep one decimal,
    '0.75'/'0.25' keep two."""
    text = f"{multiplier:.2f}"
    return text[:-1] if text.endswith("0") else text


def get_mobilenet(multiplier, pretrained=False, ctx=None, root=None,
                  **kwargs):
    net = MobileNet(multiplier, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, f"mobilenet{_store_suffix(multiplier)}",
                        root=root, ctx=ctx)
    return net


def get_mobilenet_v2(multiplier, pretrained=False, ctx=None, root=None,
                     **kwargs):
    net = MobileNetV2(multiplier, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, f"mobilenetv2_{_store_suffix(multiplier)}",
                        root=root, ctx=ctx)
    return net


def _register_factories():
    for mult in (1.0, 0.75, 0.5, 0.25):
        tag = str(mult).replace(".", "_")
        for ver, factory in ((1, get_mobilenet), (2, get_mobilenet_v2)):
            name = f"mobilenet{tag}" if ver == 1 else f"mobilenet_v2_{tag}"

            def _f(mult=mult, factory=factory, **kwargs):
                return factory(mult, **kwargs)
            _f.__name__ = name
            _f.__qualname__ = name
            _f.__doc__ = f"MobileNet v{ver}, width multiplier {mult}."
            globals()[name] = _f


_register_factories()
