"""Gluon AlexNet (Krizhevsky et al. 2012, the one-column variant used by
torchvision and the reference model zoo).

API parity with ``python/mxnet/gluon/model_zoo/vision/alexnet.py``.

CONTRACT CONSTRAINT: layer construction order is pinned by the reference
checkpoint's parameter names (``alexnet0_conv0_weight``...); the
table-driven builder below reproduces that order from the paper's
architecture, not the reference's statement sequence.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["AlexNet", "alexnet"]

# Convolutional stem: (channels, kernel, stride, pad, maxpool-after?).
_STEM = [
    (64, 11, 4, 2, True),
    (192, 5, 1, 2, True),
    (384, 3, 1, 1, False),
    (256, 3, 1, 1, False),
    (256, 3, 1, 1, True),
]

_HEAD_WIDTH = 4096
_DROP_RATE = 0.5


def _build_features():
    seq = nn.HybridSequential(prefix="")
    with seq.name_scope():
        for ch, k, s, p, pool_after in _STEM:
            seq.add(nn.Conv2D(ch, kernel_size=k, strides=s, padding=p,
                              activation="relu"))
            if pool_after:
                seq.add(nn.MaxPool2D(pool_size=3, strides=2))
        seq.add(nn.Flatten())
        for _ in range(2):
            seq.add(nn.Dense(_HEAD_WIDTH, activation="relu"))
            seq.add(nn.Dropout(_DROP_RATE))
    return seq


class AlexNet(HybridBlock):
    """Five relu convs (pools after 1, 2 and 5) then two dropout-regularised
    4096-wide relu Dense layers and a linear classifier."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = _build_features()
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, ctx=None, root=None, **kwargs):
    """AlexNet factory; ``pretrained=True`` loads from the local model store."""
    net = AlexNet(**kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "alexnet", root=root, ctx=ctx)
    return net
