"""Gluon VGG 11/13/16/19, plain and batch-normalised (Simonyan & Zisserman
1409.1556, configurations A/B/D/E).

API parity with ``python/mxnet/gluon/model_zoo/vision/vgg.py``.

CONTRACT CONSTRAINT: checkpoint parameter names pin the construction order
of parametered layers; the block-table builder below re-derives that order
from the paper's configuration table.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from ....initializer import Xavier

__all__ = ["VGG", "get_vgg", "vgg11", "vgg13", "vgg16", "vgg19",
           "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn"]

# Paper table 1: convs-per-block for each depth; widths are shared.
vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
            13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
            16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
            19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}

_CONV_INIT = dict(
    weight_initializer=Xavier(rnd_type="gaussian", factor_type="out",
                              magnitude=2),
    bias_initializer="zeros")


class VGG(HybridBlock):
    """Stacked 3x3-conv blocks (each followed by a 2x2 maxpool), then the
    classic 4096-4096-classes head with dropout."""

    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        if len(layers) != len(filters):
            raise ValueError("one filter width per conv block required")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for n_convs, width in zip(layers, filters):
                self._add_block(n_convs, width, batch_norm)
            for _ in range(2):
                self.features.add(nn.Dense(4096, activation="relu",
                                           weight_initializer="normal",
                                           bias_initializer="zeros"))
                self.features.add(nn.Dropout(rate=0.5))
            self.output = nn.Dense(classes, weight_initializer="normal",
                                   bias_initializer="zeros")

    def _add_block(self, n_convs, width, batch_norm):
        for _ in range(n_convs):
            self.features.add(nn.Conv2D(width, kernel_size=3, padding=1,
                                        **_CONV_INIT))
            if batch_norm:
                self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
        self.features.add(nn.MaxPool2D(strides=2))

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_vgg(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    """VGG-``num_layers`` factory; ``pretrained=True`` loads
    ``vgg{N}[_bn]`` from the local model store."""
    net = VGG(*vgg_spec[num_layers], **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        suffix = "_bn" if kwargs.get("batch_norm") else ""
        load_pretrained(net, f"vgg{num_layers}{suffix}", root=root, ctx=ctx)
    return net


def _register_factories():
    for depth in sorted(vgg_spec):
        for bn in (False, True):
            name = f"vgg{depth}_bn" if bn else f"vgg{depth}"

            def _factory(depth=depth, bn=bn, **kwargs):
                if bn:
                    kwargs["batch_norm"] = True
                return get_vgg(depth, **kwargs)
            _factory.__name__ = name
            _factory.__qualname__ = name
            _factory.__doc__ = (f"VGG-{depth} model"
                                + (" with batch normalisation." if bn else "."))
            globals()[name] = _factory


_register_factories()
