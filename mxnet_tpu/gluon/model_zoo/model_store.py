"""Local pretrained-weight store.

Reference parity: python/mxnet/gluon/model_zoo/model_store.py:1 — the
reference resolves ``pretrained=True`` to a ``.params`` file in
``~/.mxnet/models``, downloading on miss. This environment has no
network egress, so the store is LOCAL-ONLY: the same root layout
(``{root}/{name}.params``), populated by converting reference model-zoo
checkpoints with ``tools/convert_params.py`` (which maps the reference's
gluon parameter naming onto this framework's and rewrites the file in
the interoperable reference byte format).
"""
from __future__ import annotations

import os

from ...base import MXNetError

__all__ = ["get_model_file", "default_root"]


def default_root():
    return os.environ.get(
        "MXNET_HOME",
        os.path.join(os.path.expanduser("~"), ".mxnet")) + "/models"


def get_model_file(name, root=None):
    """Path of the local weight file for ``name`` (reference
    model_store.get_model_file, minus the download)."""
    root = os.path.expanduser(root or default_root())
    path = os.path.join(root, "%s.params" % name)
    if os.path.exists(path):
        return path
    raise MXNetError(
        "pretrained weights for '%s' not found at %s. This store is "
        "local-only (no network egress): convert a reference model-zoo "
        "checkpoint with\n"
        "  python tools/convert_params.py --model %s "
        "--in <reference>.params --root %s\n"
        "or place a compatible .params file there yourself."
        % (name, path, name, root))


def load_pretrained(net, name, root=None, ctx=None):
    """Load ``{root}/{name}.params`` into ``net`` (the tail of the
    reference's ``get_model_file`` + ``load_params`` flow)."""
    path = get_model_file(name, root)
    net.load_parameters(path, ctx=ctx)
    return net
