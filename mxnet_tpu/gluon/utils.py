"""Gluon utilities (reference python/mxnet/gluon/utils.py).

``split_and_load`` keeps its API but on TPU the idiomatic path is a single
mesh-sharded array: with one logical device the split collapses to a
device_put; with a ctx list it slices like the reference.
"""
from __future__ import annotations

import hashlib

from ..base import MXNetError

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray along ``batch_axis`` into ``num_slice`` slices
    (reference gluon/utils.py:28)."""
    from .. import ndarray as nd
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(
            "Too many slices for data with shape %s. Arguments are "
            "num_slice=%d and batch_axis=%d." % (str(data.shape), num_slice,
                                                 batch_axis))
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices "
            "along axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data." %
            (str(data.shape), num_slice, batch_axis, num_slice))
    step = size // num_slice
    if not even_split:
        slices = [
            nd.slice_axis(data, axis=batch_axis, begin=i * step,
                          end=(i + 1) * step if i < num_slice - 1 else size)
            for i in range(num_slice)]
    else:
        slices = [nd.slice_axis(data, axis=batch_axis, begin=i * step,
                                end=(i + 1) * step)
                  for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data along batch_axis and load each slice onto a ctx
    (reference gluon/utils.py:69)."""
    from .. import ndarray as nd
    from ..ndarray import NDArray
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Rescale arrays so that the sum of their 2-norms is at most max_norm
    (reference gluon/utils.py:99)."""
    import math
    if not arrays:
        raise ValueError("arrays must not be empty")
    # reduce on device, one host sync at the end (reference asscalar's once)
    total = (arrays[0] * arrays[0]).sum()
    for arr in arrays[1:]:
        total = total + (arr * arr).sum()
    total_norm = math.sqrt(float(total.asnumpy()))
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total_norm


def check_sha1(filename, sha1_hash):
    """Check a file against its expected sha1 (reference gluon/utils.py:131)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    """Reference gluon/utils.py:155 — unavailable here: the build
    environment has no network egress. Raises with guidance."""
    raise MXNetError(
        "download() is unavailable: this environment has no network access. "
        "Place the file at the target path manually (url=%s)." % url)
