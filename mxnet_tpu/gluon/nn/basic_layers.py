"""Basic layers (reference python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as _np

from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation",
           "Dropout", "BatchNorm", "InstanceNorm", "LayerNorm", "Embedding",
           "Flatten", "Lambda", "HybridLambda", "LeakyReLU"]


class Sequential(Block):
    """Stack of Blocks executed sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the best performance.",
                stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks; hybridizes into one XLA computation."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference basic_layers.py Dense; lowers to one
    MXU matmul via the FullyConnected op)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _infer_param_shapes(self, x):
        if self._flatten:
            in_units = int(_np.prod(x.shape[1:]))
        else:
            in_units = x.shape[-1]
        self.weight._shape_from_data((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units,
                               flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return "Dense(%s -> %s, %s)" % (
            shape[1] if shape[1] else None, shape[0],
            "linear" if self.act is None else self.act)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "Activation(%s)" % self._act_type


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """Batch normalization with running stats as non-differentiable
    parameters (reference basic_layers.py BatchNorm)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def _infer_param_shapes(self, x):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p._shape_from_data((ch,))

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def _infer_param_shapes(self, x):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            p._shape_from_data((ch,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def _infer_param_shapes(self, x):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            p._shape_from_data((ch,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function as a Block (reference basic_layers.py Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            assert hasattr(nd, function), \
                "Function name %s is not found in ndarray." % function
            self._func_impl = getattr(nd, function)
            self._func_name = function
        else:
            self._func_impl = function
            self._func_name = function.__name__
        self._func = self._func_impl

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return "Lambda(%s)" % self._func_name


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            from ... import symbol as sym
            assert hasattr(nd, function) and hasattr(sym, function), \
                "Function name %s is not found in ndarray/symbol." % function
            self._func_name = function

            def _f(F, *args):
                return getattr(F, function)(*args)
            self._func = _f
        else:
            self._func = lambda F, *args: function(F, *args)
            self._func_name = function.__name__

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return "HybridLambda(%s)" % self._func_name
