"""Core gluon layers: sequentials, Dense, activations, dropout, norms,
embedding.

API parity: python/mxnet/gluon/nn/basic_layers.py (same classes, same
constructor signatures, same ``gamma``/``beta``/``running_*`` parameter
names).  Re-derived around shared helpers: one sequencing mixin for both
Sequential flavours, and one gamma/beta registration helper for the three
normalisation layers.  Every hybrid layer lowers to a registered op, so a
hybridized stack compiles to a single fused XLA computation.
"""
from __future__ import annotations

import numpy as _np

from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation",
           "Dropout", "BatchNorm", "InstanceNorm", "LayerNorm", "Embedding",
           "Flatten", "Lambda", "HybridLambda", "LeakyReLU"]


class _ChainMixin:
    """add/len/index/iterate over registered children, shared by both
    sequential containers."""

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def _chain(self):
        return list(self._children.values())

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def __getitem__(self, key):
        picked = self._chain()[key]
        if not isinstance(key, slice):
            return picked
        view = type(self)(prefix=self._prefix)
        with view.name_scope():
            view.add(*picked)
        return view


class Sequential(_ChainMixin, Block):
    """Imperative chain of Blocks."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the best performance.",
                stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(_ChainMixin, HybridBlock):
    """Chain of HybridBlocks; hybridizes into one XLA computation."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x


class Dense(HybridBlock):
    """Fully-connected layer — one MXU matmul via the FullyConnected op.
    ``flatten=True`` collapses all trailing axes first (reference
    semantics)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            self.bias = self.params.get(
                "bias", shape=(units,), dtype=dtype, init=bias_initializer,
                allow_deferred_init=True) if use_bias else None
            self.act = None if activation is None else \
                Activation(activation, prefix=activation + "_")

    def _infer_param_shapes(self, x):
        width = int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight._shape_from_data((self._units, width))

    def hybrid_forward(self, F, x, weight, bias=None):
        y = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                             num_hidden=self._units, flatten=self._flatten)
        return y if self.act is None else self.act(y)

    def __repr__(self):
        w = self.weight.shape
        head = "linear" if self.act is None else self.act
        return f"Dense({w[1] if w[1] else None} -> {w[0]}, {head})"


class Activation(HybridBlock):
    """Named elementwise nonlinearity (relu/sigmoid/tanh/softrelu...)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation  # read by _alias() in Block.__init__
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    """max(x, alpha*x)."""

    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class Dropout(HybridBlock):
    """Inverted dropout; ``axes`` selects broadcast (shared-mask) axes."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


def _register_affine(layer, scale, center, in_channels, gamma_init,
                     beta_init, deferred=True):
    """Register the gamma/beta pair shared by all norm layers; a disabled
    branch becomes a frozen ('null' grad) parameter, as in the reference."""
    layer.gamma = layer.params.get(
        "gamma", grad_req="write" if scale else "null",
        shape=(in_channels,), init=gamma_init,
        allow_deferred_init=deferred, differentiable=scale)
    layer.beta = layer.params.get(
        "beta", grad_req="write" if center else "null",
        shape=(in_channels,), init=beta_init,
        allow_deferred_init=deferred, differentiable=center)


class BatchNorm(HybridBlock):
    """Batch normalisation; running statistics are frozen aux parameters
    updated inside the op (reference semantics, ``fix_gamma`` mapping
    included)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        with self.name_scope():
            _register_affine(self, scale, center, in_channels,
                             gamma_initializer, beta_initializer)
            for stat, init in (("running_mean", running_mean_initializer),
                               ("running_var", running_variance_initializer)):
                setattr(self, stat, self.params.get(
                    stat, grad_req="null", shape=(in_channels,), init=init,
                    allow_deferred_init=True, differentiable=False))

    def _infer_param_shapes(self, x):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p._shape_from_data((ch,))

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)


class InstanceNorm(HybridBlock):
    """Normalise over spatial axes per sample and channel."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            _register_affine(self, scale, center, in_channels,
                             gamma_initializer, beta_initializer)

    def _infer_param_shapes(self, x):
        ch = x.shape[self._axis]
        self.gamma._shape_from_data((ch,))
        self.beta._shape_from_data((ch,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    """Normalise over one axis (default last) per sample."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            _register_affine(self, scale, center, in_channels,
                             gamma_initializer, beta_initializer)

    def _infer_param_shapes(self, x):
        ch = x.shape[self._axis]
        self.gamma._shape_from_data((ch,))
        self.beta._shape_from_data((ch,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class Embedding(HybridBlock):
    """Index lookup into a trainable (input_dim, output_dim) table."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)


class Flatten(HybridBlock):
    """Collapse all axes after the batch axis."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


def _resolve_nd_function(name):
    from ... import ndarray as nd
    if not hasattr(nd, name):
        raise ValueError(f"Function name {name} is not found in ndarray.")
    return getattr(nd, name)


class Lambda(Block):
    """Wrap a function (or an ndarray-op name) as an eager Block."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function
            self._func_impl = _resolve_nd_function(function)
        else:
            self._func_name = function.__name__
            self._func_impl = function
        self._func = self._func_impl

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"Lambda({self._func_name})"


class HybridLambda(HybridBlock):
    """Wrap an ``f(F, x, ...)`` function (or a dual ndarray/symbol op name)
    as a hybridizable block."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            from ... import symbol as sym
            if not (hasattr(nd, function) and hasattr(sym, function)):
                raise ValueError(
                    f"Function name {function} is not found in ndarray/symbol.")
            self._func_name = function
            self._func = lambda F, *args: getattr(F, function)(*args)
        else:
            self._func_name = function.__name__
            self._func = lambda F, *args: function(F, *args)

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"
