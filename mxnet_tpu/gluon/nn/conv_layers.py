"""Convolution and pooling layers.

API parity: python/mxnet/gluon/nn/conv_layers.py (same class names, same
constructor signatures, same ``weight``/``bias`` parameter naming so
checkpoints interoperate).  Re-derived around two generic N-D cores — one
``_Conv`` handling both directions (forward / transposed) with scalar
arguments normalised per rank, and one ``_Pooling`` whose 12 public
subclasses are generated from a (kind, rank, global?) grid instead of
twelve hand-written classes.
"""
from __future__ import annotations

from ..block import HybridBlock
from .basic_layers import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose",
           "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
           "ReflectionPad2D"]

_SPATIAL_LAYOUTS = {1: "NCW", 2: "NCHW", 3: "NCDHW"}


def _per_axis(value, rank):
    """Broadcast a scalar to a rank-tuple; pass tuples through."""
    return (value,) * rank if isinstance(value, int) else tuple(value)


class _Conv(HybridBlock):
    """Rank-generic convolution.  ``output_padding=None`` selects the
    forward op; a tuple selects Deconvolution (transposed) with that
    ``adj``.  Weight layout: (out, in/g, *k) forward, (in, out/g, *k)
    transposed — the reference/cuDNN convention."""

    def __init__(self, rank, channels=0, kernel_size=0, strides=1, padding=0,
                 dilation=1, groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, output_padding=None,
                 **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        transposed = output_padding is not None
        self._op_name = "Deconvolution" if transposed else "Convolution"
        kernel = _per_axis(kernel_size, rank)
        self._kwargs = {
            "kernel": kernel, "stride": _per_axis(strides, rank),
            "dilate": _per_axis(dilation, rank),
            "pad": _per_axis(padding, rank), "num_filter": channels,
            "num_group": groups, "no_bias": not use_bias, "layout": layout}
        if transposed:
            self._kwargs["adj"] = _per_axis(output_padding, rank)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=self._weight_shape(in_channels),
                init=weight_initializer, allow_deferred_init=True)
            self.bias = self.params.get(
                "bias", shape=(channels,), init=bias_initializer,
                allow_deferred_init=True) if use_bias else None
            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None

    def _weight_shape(self, in_ch):
        g = self._kwargs["num_group"]
        k = self._kwargs["kernel"]
        if self._op_name == "Convolution":
            return (self._channels, in_ch // g if in_ch else 0) + k
        return (in_ch, self._channels // g) + k

    def _infer_param_shapes(self, x):
        self.weight._shape_from_data(self._weight_shape(x.shape[1]))

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        call_kwargs = dict(self._kwargs, no_bias=bias is None)
        out = op(x, weight, **call_kwargs) if bias is None \
            else op(x, weight, bias, **call_kwargs)
        return self.act(out) if self.act is not None else out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']})")


def _forward_conv_init(rank):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout=_SPATIAL_LAYOUTS[rank],
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        _Conv.__init__(self, rank, channels, kernel_size, strides, padding,
                       dilation, groups, layout, activation, use_bias,
                       weight_initializer, bias_initializer, in_channels,
                       None, **kwargs)
    return __init__


def _transposed_conv_init(rank):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1,
                 layout=_SPATIAL_LAYOUTS[rank], activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        _Conv.__init__(self, rank, channels, kernel_size, strides, padding,
                       dilation, groups, layout, activation, use_bias,
                       weight_initializer, bias_initializer, in_channels,
                       output_padding, **kwargs)
    return __init__


class _Pooling(HybridBlock):
    """Rank-generic pooling over the trailing spatial axes."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {
            "kernel": pool_size,
            "stride": pool_size if strides is None else strides,
            "pad": padding, "pool_type": pool_type,
            "global_pool": global_pool,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']}, "
                f"pad={self._kwargs['pad']})")


def _pool_init(rank, kind, with_count_arg):
    if with_count_arg:
        def __init__(self, pool_size=2, strides=None, padding=0,
                     layout=_SPATIAL_LAYOUTS[rank], ceil_mode=False,
                     count_include_pad=True, **kwargs):
            _Pooling.__init__(
                self, _per_axis(pool_size, rank),
                None if strides is None else _per_axis(strides, rank),
                _per_axis(padding, rank), ceil_mode, False, kind,
                count_include_pad, **kwargs)
    else:
        def __init__(self, pool_size=2, strides=None, padding=0,
                     layout=_SPATIAL_LAYOUTS[rank], ceil_mode=False,
                     **kwargs):
            _Pooling.__init__(
                self, _per_axis(pool_size, rank),
                None if strides is None else _per_axis(strides, rank),
                _per_axis(padding, rank), ceil_mode, False, kind, **kwargs)
    return __init__


def _global_pool_init(rank, kind):
    def __init__(self, layout=_SPATIAL_LAYOUTS[rank], **kwargs):
        _Pooling.__init__(self, (1,) * rank, None, (0,) * rank, True, True,
                          kind, **kwargs)
    return __init__


def _register_layer_classes():
    """Stamp out the public per-rank classes from the generic cores."""
    for rank in (1, 2, 3):
        suffix = f"{rank}D"
        for name, init in ((f"Conv{suffix}", _forward_conv_init(rank)),
                           (f"Conv{suffix}Transpose",
                            _transposed_conv_init(rank))):
            globals()[name] = type(name, (_Conv,), {
                "__init__": init, "__module__": __name__,
                "__doc__": f"{rank}-D {'transposed ' if 'Transpose' in name else ''}"
                           f"convolution layer (API parity with the "
                           f"reference {name})."})
        for kind in ("max", "avg"):
            pool_name = f"{kind.capitalize()}Pool{suffix}"
            globals()[pool_name] = type(pool_name, (_Pooling,), {
                "__init__": _pool_init(rank, kind, kind == "avg"),
                "__module__": __name__,
                "__doc__": f"{rank}-D {kind} pooling (API parity with the "
                           f"reference {pool_name})."})
            global_name = f"Global{pool_name}"
            globals()[global_name] = type(global_name, (_Pooling,), {
                "__init__": _global_pool_init(rank, kind),
                "__module__": __name__,
                "__doc__": f"Global {rank}-D {kind} pooling."})


_register_layer_classes()


class ReflectionPad2D(HybridBlock):
    """Reflect-pad the two trailing spatial axes; an int pads H and W
    symmetrically (8-tuple form matches the reference Pad op order)."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0) + (padding,) * 4
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._padding)
