"""Gluon: imperative / hybridizable neural-network API.

API parity: python/mxnet/gluon/__init__.py (Block, HybridBlock,
SymbolBlock, Parameter, ParameterDict, Trainer, nn, rnn, loss, data,
model_zoo). TPU-native: hybridize() compiles the block to one XLA
computation; Trainer's allreduce rides kvstore → ICI/DCN collectives.
"""
from .parameter import (Constant, DeferredInitializationError, Parameter,
                        ParameterDict)
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import contrib, data, loss, model_zoo, nn, rnn, utils

__all__ = ["Block", "HybridBlock", "SymbolBlock", "Parameter", "Constant",
           "ParameterDict", "DeferredInitializationError", "Trainer",
           "contrib", "data", "loss", "model_zoo", "nn", "rnn", "utils"]
