"""Gluon: imperative / hybridizable neural-network API.

Reference parity: python/mxnet/gluon/__init__.py (Block, HybridBlock,
SymbolBlock, Parameter, ParameterDict, Trainer, nn, rnn, loss, data,
model_zoo). TPU-native: hybridize() compiles the block to one XLA
computation; Trainer's allreduce rides kvstore → ICI/DCN collectives.
"""
from .parameter import (Parameter, Constant, ParameterDict,
                        DeferredInitializationError)
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import loss
from . import rnn
from . import data
from . import model_zoo
from . import utils
from . import contrib
