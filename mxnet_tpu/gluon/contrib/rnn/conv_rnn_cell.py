"""Convolutional recurrent cells (reference
gluon/contrib/rnn/conv_rnn_cell.py:37-420).

Hidden state is a feature map; input-to-hidden and hidden-to-hidden
transforms are convolutions with 'same' padding on the hidden path so
state shape is preserved across steps. Gate order matches the dense
cells (cuDNN: LSTM i,f,g,o; GRU r,z,n).
"""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared machinery: conv i2h/h2h params + state bookkeeping."""

    _num_gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, activation="tanh", prefix=None, params=None,
                 dims=2):
        super().__init__(prefix=prefix, params=params)
        self._dims = dims
        self._input_shape = tuple(input_shape)  # (C, *spatial)
        self._hidden_channels = hidden_channels
        self._i2h_kernel = _tuple(i2h_kernel, dims)
        self._h2h_kernel = _tuple(h2h_kernel, dims)
        for k in self._h2h_kernel:
            assert k % 2 == 1, "h2h kernel must be odd for same-padding"
        self._i2h_pad = _tuple(i2h_pad, dims)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        self._activation = activation

        in_c = self._input_shape[0]
        ng = self._num_gates
        self.i2h_weight = self.params.get(
            "i2h_weight",
            shape=(ng * hidden_channels, in_c) + self._i2h_kernel,
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(ng * hidden_channels, hidden_channels)
            + self._h2h_kernel, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(ng * hidden_channels,), init="zeros",
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(ng * hidden_channels,), init="zeros",
            allow_deferred_init=True)

        # state spatial dims after the i2h conv
        spatial = self._input_shape[1:]
        self._state_spatial = tuple(
            (s + 2 * p - k) + 1 for s, p, k in
            zip(spatial, self._i2h_pad, self._i2h_kernel))

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + self._state_spatial
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._dims:]}
                for _ in range(len(self._state_names))]

    _state_names = ("h",)

    def _convs(self, F, inputs, states, i2h_weight, h2h_weight, i2h_bias,
               h2h_bias):
        ng = self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=ng * self._hidden_channels)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=ng * self._hidden_channels)
        return i2h, h2h

    def _act(self, F, x):
        return F.Activation(x, act_type=self._activation)


class _ConvRNNCell(_BaseConvRNNCell):
    _num_gates = 1
    _state_names = ("h",)

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        out = self._act(F, i2h + h2h)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    _num_gates = 4
    _state_names = ("h", "c")

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        gates = i2h + h2h
        sl = F.SliceChannel(gates, num_outputs=4)
        i = F.Activation(sl[0], act_type="sigmoid")
        f = F.Activation(sl[1], act_type="sigmoid")
        g = self._act(F, sl[2])
        o = F.Activation(sl[3], act_type="sigmoid")
        next_c = f * states[1] + i * g
        next_h = o * self._act(F, next_c)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _num_gates = 3
    _state_names = ("h",)

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        i2h_sl = F.SliceChannel(i2h, num_outputs=3)
        h2h_sl = F.SliceChannel(h2h, num_outputs=3)
        r = F.Activation(i2h_sl[0] + h2h_sl[0], act_type="sigmoid")
        z = F.Activation(i2h_sl[1] + h2h_sl[1], act_type="sigmoid")
        n = self._act(F, i2h_sl[2] + r * h2h_sl[2])
        out = (1.0 - z) * n + z * states[0]
        return out, [out]


def _make(name, base, dims, doc_ref):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, activation="tanh", prefix=None,
                 params=None):
        base.__init__(self, input_shape, hidden_channels, i2h_kernel,
                      h2h_kernel, i2h_pad=i2h_pad, activation=activation,
                      prefix=prefix, params=params, dims=dims)

    cls = type(name, (base,), {
        "__init__": __init__,
        "__doc__": "%dD convolutional %s cell (reference "
                   "conv_rnn_cell.py %s)." % (dims, doc_ref, name),
    })
    return cls


Conv1DRNNCell = _make("Conv1DRNNCell", _ConvRNNCell, 1, "RNN")
Conv2DRNNCell = _make("Conv2DRNNCell", _ConvRNNCell, 2, "RNN")
Conv3DRNNCell = _make("Conv3DRNNCell", _ConvRNNCell, 3, "RNN")
Conv1DLSTMCell = _make("Conv1DLSTMCell", _ConvLSTMCell, 1, "LSTM")
Conv2DLSTMCell = _make("Conv2DLSTMCell", _ConvLSTMCell, 2, "LSTM")
Conv3DLSTMCell = _make("Conv3DLSTMCell", _ConvLSTMCell, 3, "LSTM")
Conv1DGRUCell = _make("Conv1DGRUCell", _ConvGRUCell, 1, "GRU")
Conv2DGRUCell = _make("Conv2DGRUCell", _ConvGRUCell, 2, "GRU")
Conv3DGRUCell = _make("Conv3DGRUCell", _ConvGRUCell, 3, "GRU")
