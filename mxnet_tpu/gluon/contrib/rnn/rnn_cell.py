"""Experimental recurrent cells (reference
gluon/contrib/rnn/rnn_cell.py)."""
from __future__ import annotations

from ...rnn.rnn_cell import ModifierCell

__all__ = ["VariationalDropoutCell"]


class VariationalDropoutCell(ModifierCell):
    """Apply the SAME dropout mask at every time step (variational /
    locked dropout, reference contrib/rnn/rnn_cell.py
    VariationalDropoutCell) to inputs, states, and/or outputs."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, p, like):
        from .... import ndarray as nd
        from .... import autograd
        if not autograd.is_training() or p <= 0.0:
            return None
        keep = 1.0 - p
        return nd.random.uniform(0.0, 1.0, shape=like.shape) \
            .__lt__(keep) / keep

    def __call__(self, inputs, states):
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(self.drop_inputs, inputs)
            if self._input_mask is not None:
                inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_mask is None:
                self._state_mask = self._mask(self.drop_states, states[0])
            if self._state_mask is not None:
                states = [states[0] * self._state_mask] + list(states[1:])
        output, states = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(self.drop_outputs, output)
            if self._output_mask is not None:
                output = output * self._output_mask
        return output, states

    def _alias(self):
        return "vardrop"
