"""gluon.contrib.nn (reference gluon/contrib/nn/basic_layers.py)."""
from .basic_layers import (Concurrent, HybridConcurrent, Identity,
                           SparseEmbedding, SyncBatchNorm)

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm"]
