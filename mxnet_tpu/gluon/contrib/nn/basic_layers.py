"""Experimental basic layers (reference
gluon/contrib/nn/basic_layers.py:29-220)."""
from __future__ import annotations

from ...nn.basic_layers import (Sequential, HybridSequential, BatchNorm,
                                Embedding)
from ...block import HybridBlock

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm"]


class Concurrent(Sequential):
    """Run children on the same input, concatenate outputs on ``axis``
    (reference basic_layers.py:29)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def __getitem__(self, key):
        out = super().__getitem__(key)
        if isinstance(out, Concurrent):
            out.axis = self.axis  # slices must keep the concat axis
        return out

    def forward(self, x):
        from .... import ndarray as nd
        outs = [block(x) for block in self._children.values()]
        return nd.concat(*outs, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (reference basic_layers.py:62)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def __getitem__(self, key):
        out = super().__getitem__(key)
        if isinstance(out, HybridConcurrent):
            out.axis = self.axis
        return out

    def hybrid_forward(self, F, x):
        outs = [block(x) for block in self._children.values()]
        return F.concat(*outs, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through block for skip connections in Concurrent
    (reference basic_layers.py:95)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """Embedding declared with row-sparse gradients (reference
    basic_layers.py:116). The compiled graph computes the weight grad as
    a dense scatter-add (XLA's efficient form); convert with
    ``nd.sparse.cast_storage(grad, 'row_sparse')`` to drive the lazy
    optimizer updates when desired."""

    def __init__(self, input_dim, output_dim, dtype="float32", **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype, **kwargs)


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (reference
    basic_layers.py:163, contrib SyncBatchNorm over an NCCL key-value
    sync). TPU-native: when the batch axis is sharded over a mesh (the
    fused TrainStep / a pjit'd step), the batch-mean/variance reductions
    inside BatchNorm run over the GLOBAL batch — XLA inserts the
    cross-device collectives during SPMD partitioning — so BatchNorm is
    already synchronized and this class only documents that;
    ``num_devices`` is accepted for API parity."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
