"""gluon.contrib — experimental layers (reference gluon/contrib/)."""
from . import nn
from . import rnn
