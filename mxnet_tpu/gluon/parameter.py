"""Parameter / ParameterDict.

Reference parity: python/mxnet/gluon/parameter.py (Parameter with deferred
initialization, grad_req, lr_mult/wd_mult; ParameterDict with prefix
scoping and sharing). TPU-native: data lives as a jax.Array-backed NDArray;
"per-context copies" (list_data/list_grad) collapse to the single sharded
array — a mesh sharding replaces per-device replication.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray, zeros as nd_zeros
from ..initializer import InitDesc, get as init_create
from .. import autograd

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default",
                 attrs=None):
        self.name = name
        self._grad_req = None
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        # free-form user attrs (e.g. __sharding__) that var() re-emits so
        # a Block -> tojson -> SymbolBlock round trip keeps them — the
        # same contract lr_mult rides through its typed field
        self.attrs = dict(attrs) if attrs else {}
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self._data = None
        self._grad = None
        self._deferred_init = ()
        self._stype = stype
        self.grad_req = grad_req

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self.shape,
                                                      self.dtype)

    # ------------------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                self._data._grad = None
                self._data._grad_req = "null"
        elif self._data is not None:
            self._init_grad()

    def _check_shape_dtype_known(self):
        if self.shape is None or any(s == 0 for s in self.shape):
            raise DeferredInitializationError(
                "Parameter '%s' has unknown shape %s. Either pass shapes or "
                "run a forward pass to trigger shape inference." %
                (self.name, self.shape))

    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if default_init is None:
            from ..initializer import Uniform
            default_init = Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self.shape is None or any(s == 0 for s in self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise ValueError(
                "Cannot initialize Parameter '%s' because it has invalid "
                "shape %s." % (self.name, self.shape))
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        self._deferred_init = ()
        nd = nd_zeros(self.shape, ctx[0], self.dtype)
        initializer = init or self.init or default_init
        if isinstance(initializer, str):
            initializer = init_create(initializer)
        initializer(InitDesc(self.name, {"__init__": ""}), nd)
        self._data = nd
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = nd_zeros(self._data.shape, self._data.context,
                              self._data.dtype)
        autograd.mark_variables([self._data], [self._grad],
                                grad_reqs=self._grad_req)

    def _finish_deferred_init(self):
        if self._deferred_init:
            init, ctx, default_init = self._deferred_init
            self._check_shape_dtype_known()
            self._finish_init(init, ctx, default_init)

    def _shape_from_data(self, data_shape):
        """Complete unknown (0) dims from a concrete forward input."""
        if self.shape is None:
            self.shape = tuple(data_shape)
            return
        new = tuple(d if s == 0 else s
                    for s, d in zip(self.shape, data_shape))
        if len(self.shape) != len(data_shape) or any(
                s != 0 and s != d for s, d in zip(self.shape, data_shape)):
            raise MXNetError(
                "Parameter %s: inferred shape %s incompatible with declared "
                "%s" % (self.name, data_shape, self.shape))
        self.shape = new

    # ------------------------------------------------------------------
    def data(self, ctx=None):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    "Parameter '%s' has not been initialized yet because "
                    "initialization was deferred. Run a forward pass first." %
                    self.name)
            raise RuntimeError(
                "Parameter '%s' has not been initialized. You should "
                "initialize parameters with Block.initialize()." % self.name)
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        if self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter '%s' because "
                "grad_req='null'" % self.name)
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [self.data().context] if self._data is not None else []

    def zero_grad(self):
        if self._grad is not None:
            self._grad[:] = 0

    def set_data(self, data):
        self.shape = tuple(data.shape)
        if self._data is None:
            if self._deferred_init:
                self._finish_deferred_init()
            else:
                raise RuntimeError("set_data on uninitialized Parameter '%s'"
                                   % self.name)
        if isinstance(data, NDArray):
            self._data._set_data(data.astype(self.dtype)._data)
        else:
            import jax.numpy as jnp
            self._data._set_data(jnp.asarray(data, self.dtype))

    def reset_ctx(self, ctx):
        pass  # single logical device; shardings govern placement

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data._set_data(self._data.astype(dtype)._data)
            if self._grad is not None:
                self._init_grad()

    def var(self):
        """Symbol variable for this parameter (used by export/SymbolBlock).
        Free-form user attrs (``self.attrs``, e.g. ``__sharding__``)
        ride along so export/tojson preserves them."""
        from .. import symbol as sym
        return sym.var(self.name, attr=self.attrs or None, shape=self.shape,
                       dtype=self.dtype, lr_mult=self.lr_mult,
                       wd_mult=self.wd_mult)


class Constant(Parameter):
    """Constant parameter: never updated (reference gluon/parameter.py
    Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            import jax.numpy as jnp
            value = NDArray(jnp.asarray(value, "float32"))
        self.value = value

        class _CInit:
            def __call__(self, desc, arr):
                arr[:] = value.asnumpy()

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit(),
                         differentiable=False)


class ParameterDict:
    """Dict of Parameters with prefix scoping + sharing
    (reference gluon/parameter.py ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        return "ParameterDict '%s' (%s)" % (
            self._prefix, ", ".join(sorted(self._params)))

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def get(self, name, **kwargs):
        """Retrieve-or-create ``prefix+name`` (the Block layer API)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            # Keep any attribute already set on a shared/existing Parameter
            # and assert consistency, instead of clobbering it with layer
            # defaults (reference gluon/parameter.py ParameterDict.get).
            for k, v in kwargs.items():
                existing = getattr(param, k, None)
                if existing is not None:
                    if k == "shape" and v is not None:
                        v = tuple(v)
                        cur = tuple(existing)
                        if len(cur) == len(v) and all(
                                a == b or a == 0 or b == 0
                                for a, b in zip(cur, v)):
                            param.shape = tuple(
                                b if a == 0 else a for a, b in zip(cur, v))
                            continue
                        raise AssertionError(
                            "Parameter '%s' shape mismatch: %s vs %s"
                            % (name, cur, v))
                    if v is not None and v != existing:
                        raise AssertionError(
                            "Parameter '%s' %s mismatch: %s vs %s"
                            % (name, k, existing, v))
                elif v is not None:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError("No constant named '%s'" % name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError("Cannot update self with other because they "
                                 "have different Parameters with the same "
                                 "name '%s'" % k)
            self._params[k] = v

    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from ..initializer import Uniform
        for _, v in sorted(self._params.items()):
            v.initialize(None, ctx, init or Uniform(), force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        pass

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..serialization import save_ndarray_file
        arg = {}
        for p in self._params.values():
            weight = p.data()
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = weight
        save_ndarray_file(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..serialization import load_ndarray_file
        loaded = load_ndarray_file(filename)
        params = {restore_prefix + k.split(":", 1)[-1]: v
                  for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in params:
                    raise IOError("Parameter '%s' is missing in file '%s'"
                                  % (name, filename))
        for name, v in params.items():
            if name not in self._params:
                if not ignore_extra:
                    raise IOError("Parameter '%s' loaded from file '%s' is "
                                  "not present in ParameterDict"
                                  % (name, filename))
                continue
            p = self._params[name]
            if p.shape is None or p._data is None:
                p.shape = v.shape
                p.initialize(ctx=ctx)
            p.set_data(v)
