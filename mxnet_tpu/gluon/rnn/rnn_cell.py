"""Recurrent cells (reference python/mxnet/gluon/rnn/rnn_cell.py).

Cell-level API: one step at a time via ``__call__(input, states)`` plus
``unroll`` over a sequence. TPU note: for long sequences prefer the fused
layers in rnn_layer.py (one ``lax.scan`` XLA while-loop); ``unroll`` here
is a Python-level unroll that XLA still fuses per step but compiles
O(length) HLO — matching the reference's explicit-unroll semantics.
"""
from __future__ import annotations

from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, F=None):
    """Normalize inputs to a list of (batch, ...) steps or a merged tensor.
    Returns (inputs, axis, F, batch_size)."""
    from ... import ndarray as F_nd
    from ...ndarray import NDArray
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = F_nd.stack(*inputs, axis=axis)
        in_list = inputs
    else:
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            seq = inputs.shape[axis]
            in_list = F_nd.split(inputs, num_outputs=seq, axis=axis,
                                 squeeze_axis=True)
            if seq == 1:
                in_list = [in_list]
            inputs = list(in_list)
    return inputs, axis, F_nd, batch_size


class RecurrentCell(Block):
    """Abstract cell (reference rnn_cell.py RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        from ... import ndarray as nd
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            if func is None:
                states.append(nd.zeros(shape, **kwargs))
            else:
                states.append(func(shape=shape, **kwargs))
        return states

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)

    def forward(self, inputs, states):
        raise NotImplementedError

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over ``length`` steps (reference rnn_cell.py:305)."""
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(length, inputs,
                                                       layout, False)
        states = begin_state if begin_state is not None else \
            self.begin_state(batch_size)
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            from ... import ndarray as nd
            # per-sequence last *valid* states, not the states after padding
            # (reference unroll applies F.SequenceLast on stacked states)
            states = [nd.SequenceLast(nd.stack(*ss, axis=0),
                                      sequence_length=valid_length,
                                      use_sequence_length=True)
                      for ss in zip(*all_states)]
            stacked = nd.stack(*outputs, axis=0)  # (T, N, C)
            masked = nd.SequenceMask(stacked, sequence_length=valid_length,
                                     use_sequence_length=True)
            outputs = nd.split(masked, num_outputs=length, axis=0,
                               squeeze_axis=True)
            if length == 1:
                outputs = [outputs]
            outputs = list(outputs)
        if merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """Cell whose step is a hybrid_forward (jit-able)."""

    def forward(self, inputs, states):
        from ... import ndarray as F
        params = {}
        for name, p in self._reg_params.items():
            try:
                params[name] = p.data()
            except Exception:
                self._infer_cell_shapes(inputs)
                for pp in self._reg_params.values():
                    pp._finish_deferred_init()
                params = {n: pp.data()
                          for n, pp in self._reg_params.items()}
                break
        return self.hybrid_forward(F, inputs, states, **params)

    def _infer_cell_shapes(self, inputs):
        pass


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(W x + b + R h + r)
    (reference rnn_cell.py RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _infer_cell_shapes(self, inputs):
        self.i2h_weight.shape = (self._hidden_size, inputs.shape[1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell, cuDNN gate order (i, f, g, o)
    (reference rnn_cell.py LSTMCell)."""

    def __init__(self, hidden_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _infer_cell_shapes(self, inputs):
        self.i2h_weight.shape = (4 * self._hidden_size, inputs.shape[1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4)
        in_gate = F.Activation(slices[0], act_type="sigmoid")
        forget_gate = F.Activation(slices[1], act_type="sigmoid")
        in_transform = F.Activation(slices[2], act_type="tanh")
        out_gate = F.Activation(slices[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell, cuDNN gate order (r, z, n)
    (reference rnn_cell.py GRUCell)."""

    def __init__(self, hidden_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _infer_cell_shapes(self, inputs):
        self.i2h_weight.shape = (3 * self._hidden_size, inputs.shape[1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h_n + reset_gate * h2h_n,
                                  act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells applied in order (reference rnn_cell.py:660)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            cell_states = states[pos:pos + n]
            pos += n
            inputs, cell_states = cell(inputs, cell_states)
            next_states.extend(cell_states)
        return inputs, next_states


class DropoutCell(RecurrentCell):
    """Dropout on cell output (reference rnn_cell.py DropoutCell)."""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def forward(self, inputs, states):
        from ... import ndarray as F
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate)
        return inputs, states


class ModifierCell(RecurrentCell):
    """Base for cells that wrap another cell
    (reference rnn_cell.py ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size=batch_size, func=func,
                                           **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def forward(self, inputs, states):
        from ... import ndarray as F
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        p_outputs, p_states = self.zoneout_outputs, self.zoneout_states

        def mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output if self._prev_output is not None \
            else F.zeros_like(next_output)
        output = F.where(mask(p_outputs, next_output), next_output,
                         prev_output) if p_outputs != 0.0 else next_output
        new_states = ([F.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0.0 else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds input to output (reference rnn_cell.py ResidualCell)."""

    def _alias(self):
        return "residual"

    def forward(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(RecurrentCell):
    """Runs l_cell forward and r_cell backward over a sequence; only usable
    via unroll (reference rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        inputs, axis, _, batch_size = _format_sequence(length, inputs,
                                                       layout, False)
        states = begin_state if begin_state is not None else \
            self.begin_state(batch_size)
        l_cell, r_cell = self._children.values()
        l_n = len(l_cell.state_info())
        def _rev(seq):
            # reverse each sequence over its valid steps only (reference
            # uses F.SequenceReverse(sequence_length=valid_length)) so the
            # backward cell starts at the last valid token, not at padding
            if valid_length is None:
                return list(reversed(seq))
            rev = F.SequenceReverse(F.stack(*seq, axis=0),
                                    sequence_length=valid_length,
                                    use_sequence_length=True)
            if length == 1:
                return [F.reshape(rev, shape=rev.shape[1:])]
            return list(F.split(rev, num_outputs=length, axis=0,
                                squeeze_axis=True))

        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:l_n], layout=layout,
            merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=_rev(inputs),
            begin_state=states[l_n:], layout=layout, merge_outputs=False,
            valid_length=valid_length)
        outputs = [F.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, _rev(r_outputs))]
        if merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, l_states + r_states
