"""Recurrent layers and cells (reference python/mxnet/gluon/rnn/)."""
from .rnn_cell import (RecurrentCell, RNNCell, LSTMCell, GRUCell,
                       SequentialRNNCell, DropoutCell, ResidualCell,
                       BidirectionalCell, ModifierCell, ZoneoutCell)
from .rnn_layer import RNN, LSTM, GRU
