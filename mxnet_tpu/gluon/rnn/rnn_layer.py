"""Fused recurrent layers RNN / LSTM / GRU.

Reference parity: python/mxnet/gluon/rnn/rnn_layer.py (_RNNLayer packing
per-layer i2h/h2h Parameters into the fused RNN op's flat weight vector,
cuDNN layout). TPU-native: the fused op (ops/rnn.py) is one ``lax.scan``
XLA while-loop per layer/direction with the input matmul hoisted onto the
MXU — the packed-layout parity means checkpoints interoperate with the
reference's cuDNN weights.
"""
from __future__ import annotations

from ..block import Block

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(Block):
    """Eager-only like the reference's 1.x ``_RNNLayer`` (a ``Block``): the
    fused op is itself one jitted ``lax.scan``, so hybridization adds
    nothing."""
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, prefix=None, params=None):
        self._mode = mode  # before super(): _alias() runs in Block.__init__
        super().__init__(prefix=prefix, params=params)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC', 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer

        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param("%s%d_i2h_weight" % (j, i),
                                     shape=(ng * nh, ni),
                                     init=i2h_weight_initializer)
                self._register_param("%s%d_h2h_weight" % (j, i),
                                     shape=(ng * nh, nh),
                                     init=h2h_weight_initializer)
                self._register_param("%s%d_i2h_bias" % (j, i),
                                     shape=(ng * nh,),
                                     init=i2h_bias_initializer)
                self._register_param("%s%d_h2h_bias" % (j, i),
                                     shape=(ng * nh,),
                                     init=h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "%s -> %s" % (shape[1] if shape[1] else None,
                                shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def _alias(self):
        return self._mode

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states (reference rnn_layer.py begin_state)."""
        from ... import ndarray as nd
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            if func is None:
                states.append(nd.zeros(shape, **kwargs))
            else:
                states.append(func(shape=shape, **kwargs))
        return states

    def _infer_param_shapes(self, inputs):
        ni = inputs.shape[2]  # called with TNC inputs
        ng, nh = self._gates, self._hidden_size
        for j in ["l", "r"][:self._dir]:
            getattr(self, "%s0_i2h_weight" % j).shape = (ng * nh, ni)

    def forward(self, inputs, states=None):
        """Accepts layout ``self._layout``; states optional
        (reference rnn_layer.py forward_kernel/forward)."""
        from ... import ndarray as nd
        batch_size = inputs.shape[self._layout.find("N")]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=inputs.context,
                                      dtype=str(inputs.dtype))
        if isinstance(states, nd.NDArray):
            states = [states]
        for info, state in zip(self.state_info(batch_size), states):
            if state.shape != info["shape"]:
                raise ValueError(
                    "Invalid recurrent state shape. Expecting %s, got %s." %
                    (str(info["shape"]), str(state.shape)))
        out = self._forward_kernel(inputs, states)
        # out: (output, states); skip states in return if not given
        return out[0] if skip_states else out

    def _forward_kernel(self, inputs, states):
        from ... import ndarray as F
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        # pack flat params in the fused op's cuDNN layout: all weights
        # (per layer, per dir: i2h then h2h) then all biases
        if any(p._data is None for p in self._reg_params.values()):
            self._infer_param_shapes(inputs)
            for p in self._reg_params.values():
                p._finish_deferred_init()
        wbits, bbits = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                wbits.append(getattr(self, "%s%d_i2h_weight" % (j, i))
                             .data().reshape((-1,)))
                wbits.append(getattr(self, "%s%d_h2h_weight" % (j, i))
                             .data().reshape((-1,)))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                bbits.append(getattr(self, "%s%d_i2h_bias" % (j, i))
                             .data().reshape((-1,)))
                bbits.append(getattr(self, "%s%d_h2h_bias" % (j, i))
                             .data().reshape((-1,)))
        params = F.concat(*(wbits + bbits), dim=0)

        rnn_args = [inputs, params] + list(states)
        if self._mode != "lstm":
            rnn_args = rnn_args[:3]
        rnn = F.RNN(*rnn_args, state_size=self._hidden_size,
                    num_layers=self._num_layers, bidirectional=self._dir == 2,
                    p=self._dropout, state_outputs=True, mode=self._mode)
        if self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        return outputs, states


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (relu or tanh), fused
    (reference rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM, fused (reference rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU, fused (reference rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
