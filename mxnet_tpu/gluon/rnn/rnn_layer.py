"""Fused recurrent layers RNN / LSTM / GRU.

API parity: python/mxnet/gluon/rnn/rnn_layer.py (same constructors, same
``l{i}_i2h_weight``-style parameter names, same packed flat-weight layout
as the reference's cuDNN path so checkpoints interoperate).  TPU-native:
the fused op (ops/rnn.py) is one ``lax.scan`` XLA while-loop per
layer/direction with the input matmul hoisted onto the MXU.  Layers are
eager-only ``Block``s like the reference's 1.x `_RNNLayer` — the fused op
is itself a single jitted scan, so hybridization would add nothing.
"""
from __future__ import annotations

from ..block import Block

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _FusedRecurrent(Block):
    """Common machinery: a grid of per-layer/per-direction i2h/h2h params,
    packed on demand into the fused op's flat vector (all weights, then all
    biases, each layer-major then direction-major, i2h before h2h)."""

    #: number of recurrent state tensors (LSTM overrides with 2)
    _state_arity = 1

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, prefix=None, params=None):
        self._mode = mode  # read by _alias() inside Block.__init__
        super().__init__(prefix=prefix, params=params)
        if layout not in ("TNC", "NTC"):
            raise ValueError(
                f"Invalid layout {layout}; must be one of ['TNC', 'NTC']")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = _GATES[mode]

        inits = {"i2h_weight": i2h_weight_initializer,
                 "h2h_weight": h2h_weight_initializer,
                 "i2h_bias": i2h_bias_initializer,
                 "h2h_bias": h2h_bias_initializer}
        for name, shape in self._param_grid(input_size):
            kind = name.split("_", 1)[1]
            param = self.params.get(name, shape=shape, init=inits[kind],
                                    allow_deferred_init=True)
            setattr(self, name, param)

    def _alias(self):
        return self._mode

    def _directions(self):
        return ("l", "r")[:self._dir]

    def _param_grid(self, input_size):
        """Yield (param_name, shape) for every layer x direction x kind."""
        rows = self._gates * self._hidden_size
        width_in = input_size
        for layer in range(self._num_layers):
            for d in self._directions():
                yield f"{d}{layer}_i2h_weight", (rows, width_in)
                yield f"{d}{layer}_h2h_weight", (rows, self._hidden_size)
                yield f"{d}{layer}_i2h_bias", (rows,)
                yield f"{d}{layer}_h2h_bias", (rows,)
            width_in = self._hidden_size * self._dir

    def __repr__(self):
        w = self.l0_i2h_weight.shape
        mapping = f"{w[1] if w[1] else None} -> {w[0] // self._gates}"
        opts = "" if self._num_layers == 1 else f", num_layers={self._num_layers}"
        if self._dropout:
            opts += f", dropout={self._dropout}"
        if self._dir == 2:
            opts += ", bidirectional"
        return f"{type(self).__name__}({mapping}, {self._layout}{opts})"

    # -- states ---------------------------------------------------------
    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"}
                for _ in range(self._state_arity)]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Zero (or ``func``-built) initial states for a batch."""
        from ... import ndarray as nd
        make = func or (lambda shape, **kw: nd.zeros(shape, **kw))
        return [make(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    # -- forward --------------------------------------------------------
    def forward(self, inputs, states=None):
        """Run the fused recurrence.  ``states`` optional — when omitted,
        zeros are used and only the output sequence is returned."""
        from ... import ndarray as nd
        batch = inputs.shape[self._layout.index("N")]
        implicit = states is None
        if implicit:
            states = self.begin_state(batch, ctx=inputs.context,
                                      dtype=str(inputs.dtype))
        elif isinstance(states, nd.NDArray):
            states = [states]
        for info, state in zip(self.state_info(batch), states):
            if state.shape != info["shape"]:
                raise ValueError(
                    f"Invalid recurrent state shape. Expecting "
                    f"{info['shape']}, got {state.shape}.")
        outputs, out_states = self._run_fused(inputs, states)
        return outputs if implicit else (outputs, out_states)

    def _packed_params(self, F):
        """Late-bind deferred shapes from the first input, then concatenate
        the parameter grid into the fused op's flat layout."""
        def flat(name):
            return getattr(self, name).data().reshape((-1,))
        weights = []
        for i in range(self._num_layers):
            for d in self._directions():
                weights += [flat(f"{d}{i}_i2h_weight"),
                            flat(f"{d}{i}_h2h_weight")]
        biases = []
        for i in range(self._num_layers):
            for d in self._directions():
                biases += [flat(f"{d}{i}_i2h_bias"), flat(f"{d}{i}_h2h_bias")]
        return F.concat(*(weights + biases), dim=0)

    def _run_fused(self, inputs, states):
        from ... import ndarray as F
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        if any(p._data is None for p in self._reg_params.values()):
            # first call: bind layer-0 input width, then materialise
            rows = self._gates * self._hidden_size
            for d in self._directions():
                getattr(self, f"{d}0_i2h_weight").shape = \
                    (rows, inputs.shape[2])
            for p in self._reg_params.values():
                p._finish_deferred_init()
        args = [inputs, self._packed_params(F), *states]
        if self._mode != "lstm":
            args = args[:3]
        result = F.RNN(*args, state_size=self._hidden_size,
                       num_layers=self._num_layers,
                       bidirectional=self._dir == 2, p=self._dropout,
                       state_outputs=True, mode=self._mode)
        outputs = result[0]
        out_states = list(result[1:1 + self._state_arity])
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        return outputs, out_states


class RNN(_FusedRecurrent):
    """Multi-layer Elman RNN with relu or tanh activation, fused."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, **kwargs)


class LSTM(_FusedRecurrent):
    """Multi-layer LSTM, fused; carries (h, c) state pair."""

    _state_arity = 2

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "lstm", **kwargs)


class GRU(_FusedRecurrent):
    """Multi-layer GRU, fused."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "gru", **kwargs)
