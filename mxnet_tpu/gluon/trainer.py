"""Gluon Trainer: applies an Optimizer to a set of Parameters.

Reference parity: python/mxnet/gluon/trainer.py:62-334 (kvstore-backed
``step = _allreduce_grads + _update``, ``update_on_kvstore``,
``compression_params``, state save/load).

TPU-native: a "device list" collapses to one logical sharded array, so the
allreduce is the kvstore push/pull (identity single-process, ICI psum when
the values are mesh-sharded, DCN collective under dist kvstores) — the
optimizer math itself is the fused jit update ops in ops/optimizer_ops.py.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from .. import kvstore as kvs
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    """One ``step()`` = reduce grads across replicas + apply the optimizer.
    Keys on the kvstore are the parameters' positional indices."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        self._params = self._normalize_params(params)
        self._param2idx = {p.name: i for i, p in enumerate(self._params)}
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {"kvstore": kvstore,
                                "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None

    @staticmethod
    def _normalize_params(params):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys())]
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                f"First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        for p in params:
            if not isinstance(p, Parameter):
                raise ValueError(
                    f"First argument must be a list or dict of Parameters, "
                    f"got list of {type(p)}.")
        return list(params)

    def _trainable(self):
        """(index, param) pairs that receive gradients."""
        return ((i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null")

    def _require_worker_side_update(self, what):
        if self._kvstore and self._update_on_kvstore:
            raise AssertionError(
                f"{what} when parameters are updated on kvstore is not "
                f"supported. Try setting `update_on_kvstore` to False "
                f"when creating trainer.")

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) != {"rescale_grad"}:
                raise ValueError(
                    "optimizer_params must be None if optimizer is an "
                    "instance of Optimizer instead of str")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        if kvstore is None:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = kvs.create(kvstore) if isinstance(kvstore, str) else kvstore
            if update_on_kvstore is None:
                update_on_kvstore = True
            if self._compression_params is not None:
                kv.set_gradient_compression(self._compression_params)
                # with compression the reference forces updates onto workers
                # only for row_sparse; 2bit runs fine on the store
            self._kvstore = kv
            self._update_on_kvstore = update_on_kvstore
            for i, param in self._trainable():
                kv.init(i, param.data())
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its "
                              "learning rate can be accessed.")
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its "
                              "learning rate is mutated.")
        self._optimizer.set_learning_rate(lr)

    # ------------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """Make one parameter update: allreduce grads then apply the
        optimizer (reference trainer.py:241)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        """Reduce gradients over devices/workers WITHOUT updating — only
        valid with update_on_kvstore=False (reference trainer.py:276)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._require_worker_side_update("allreduce_grads()")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        # one batched push (then pull) over every trainable param so the
        # bucketed kvstore hot path can pack the full keyset into compiled
        # buckets; per-key priority -i keeps reference dispatch order
        keys, grads, prios = [], [], []
        for i, param in self._trainable():
            keys.append(i)
            grads.append(param.list_grad())
            prios.append(-i)
        if not keys:
            return
        self._kvstore.push(keys, grads, priority=prios)
        if not self._update_on_kvstore:
            self._kvstore.pull(keys, out=grads)

    def update(self, batch_size, ignore_stale_grad=False):
        """Apply optimizer only — only valid with update_on_kvstore=False
        (reference trainer.py:300)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._require_worker_side_update("update()")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        store_side = self._kvstore and self._update_on_kvstore
        pull_keys, pull_outs = [], []
        for i, param in self._trainable():
            if param._data is None:
                if ignore_stale_grad:
                    continue
                raise UserWarning(
                    f"Gradient of Parameter `{param.name}` has not been "
                    f"initialized")
            if store_side:
                pull_keys.append(i)
                pull_outs.append(param.list_data())
                continue
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)
        if pull_keys:
            # ONE batched pull over every trainable param (a per-key
            # pull call per parameter would re-enter the kvstore sync
            # point N times per step)
            self._kvstore.pull(pull_keys, out=pull_outs)

    # ------------------------------------------------------------------
    def save_states(self, fname):
        """(reference trainer.py:312)"""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """(reference trainer.py:330)"""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for upd in self._updaters:
                upd.set_states(states)
            # adopt the deserialized optimizer (num_update, hyperparams) —
            # reference trainer.py load_states does the same
            self._optimizer = self._updaters[0].optimizer
            for upd in self._updaters:
                upd.optimizer = self._optimizer
        self._optimizer.param_dict = {
            i: param for i, param in enumerate(self._params)}
