"""Vision datasets + transforms (reference gluon/data/vision/)."""
from .datasets import (MNIST, FashionMNIST, CIFAR10, CIFAR100,
                       ImageRecordDataset, ImageFolderDataset)
from . import transforms

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset", "transforms"]
