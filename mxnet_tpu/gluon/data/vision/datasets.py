"""Vision datasets (reference python/mxnet/gluon/data/vision.py).

No network egress in this environment: datasets read standard local files
(idx/pickle/folder formats) from ``root`` and raise with guidance when the
files are absent, instead of downloading.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ....base import MXNetError
from ..dataset import Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _require(path):
    if not os.path.exists(path):
        raise MXNetError(
            "Dataset file %s not found. This environment has no network "
            "access; place the file there manually." % path)
    return path


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._root = os.path.expanduser(root)
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        from .... import ndarray as nd
        data = nd.array(self._data[idx])
        if self._transform is not None:
            return self._transform(data, self._label[idx])
        return data, self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST (reference data/vision.py MNIST); reads idx files from root."""

    _files = {True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
              False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")}

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_idx(self, path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            data = f.read()
        magic, = struct.unpack(">i", data[:4])
        ndim = magic % 256
        dims = struct.unpack(">" + "i" * ndim, data[4:4 + 4 * ndim])
        return np.frombuffer(data, dtype=np.uint8,
                             offset=4 + 4 * ndim).reshape(dims)

    def _get_data(self):
        img_name, lbl_name = self._files[self._train]
        img_path = os.path.join(self._root, img_name)
        if not os.path.exists(img_path) and os.path.exists(img_path + ".gz"):
            img_path += ".gz"
        lbl_path = os.path.join(self._root, lbl_name)
        if not os.path.exists(lbl_path) and os.path.exists(lbl_path + ".gz"):
            lbl_path += ".gz"
        imgs = self._read_idx(_require(img_path))
        self._data = imgs.reshape(imgs.shape[0], imgs.shape[1],
                                  imgs.shape[2], 1)
        self._label = self._read_idx(_require(lbl_path)).astype(np.int32)


class FashionMNIST(MNIST):
    """FashionMNIST: same idx format, different files
    (reference data/vision.py FashionMNIST)."""

    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the python pickle batches
    (reference data/vision.py CIFAR10)."""

    _train_files = ["data_batch_%d" % i for i in range(1, 6)]
    _test_files = ["test_batch"]

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, path):
        import pickle
        with open(_require(path), "rb") as f:
            batch = pickle.load(f, encoding="latin1")
        data = np.asarray(batch["data"], dtype=np.uint8)
        data = data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        labels = np.asarray(
            batch.get("labels", batch.get("fine_labels")), dtype=np.int32)
        return data, labels

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        base = self._root
        sub = os.path.join(base, "cifar-10-batches-py")
        if os.path.isdir(sub):
            base = sub
        data, labels = [], []
        for fname in files:
            d, l = self._read_batch(os.path.join(base, fname))
            data.append(d)
            labels.append(l)
        self._data = np.concatenate(data, axis=0)
        self._label = np.concatenate(labels, axis=0)


class CIFAR100(CIFAR10):
    """CIFAR100 (reference data/vision.py CIFAR100)."""

    _train_files = ["train"]
    _test_files = ["test"]

    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 transform=None):
        sub = os.path.join(os.path.expanduser(root), "cifar-100-python")
        if os.path.isdir(sub):
            root = sub
        super().__init__(root, train, transform)


class ImageRecordDataset(RecordFileDataset):
    """Images + labels from a .rec file (reference data/vision.py
    ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack_img
        record = super().__getitem__(idx)
        header, img = unpack_img(record, iscolor=self._flag)
        from .... import ndarray as nd
        img = nd.array(img)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """root/category/image.jpg layout (reference data/vision.py
    ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from ....image import imread
        img = imread(self.items[idx][0], flag=self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
