"""Gluon vision transforms.

Reference parity: python/mxnet/gluon/data/vision/transforms.py
(Compose, Cast, ToTensor, Normalize, RandomResizedCrop, CenterCrop,
Resize, RandomFlipLeftRight/TopBottom, RandomBrightness/Contrast/
Saturation/Hue, RandomColorJitter, RandomLighting). Transforms operate
on HWC uint8/float images until ToTensor flips to CHW float [0, 1] —
same contract as the reference; the jitter math reuses mx.image's
augmenters (image.py BrightnessJitterAug etc.) so DataLoader pipelines
and ImageIter pipelines share one implementation.
"""
from __future__ import annotations

import numpy as np

from ....ndarray import NDArray, array
from .... import image as _image
from ...block import Block, HybridBlock
from ...nn import Sequential


__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "RandomResizedCrop",
           "CenterCrop", "Resize", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting"]


def _as_nd(x):
    return x if isinstance(x, NDArray) else array(np.asarray(x))


class Compose(Sequential):
    """Sequentially apply child transforms (ref transforms.py Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    """Cast to dtype (ref transforms.py Cast)."""

    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """(H, W, C) or (N, H, W, C) uint8 [0,255] -> (C, H, W) float32
    [0,1] (ref transforms.py ToTensor)."""

    def hybrid_forward(self, F, x):
        out = F.cast(x, dtype="float32") / 255.0
        if len(x.shape) == 4:
            return F.transpose(out, axes=(0, 3, 1, 2))
        return F.transpose(out, axes=(2, 0, 1))


class Normalize(HybridBlock):
    """Channel-wise (x - mean) / std on CHW tensors
    (ref transforms.py Normalize)."""

    def __init__(self, mean, std):
        super().__init__()
        self._mean = tuple(np.ravel(mean).tolist())
        self._std = tuple(np.ravel(std).tolist())

    def hybrid_forward(self, F, x):
        # one fused op with static mean/std attrs — hybridize-safe, no
        # per-call constant uploads (ref uses the image.normalize op too)
        return F.image_normalize(x, mean=self._mean, std=self._std)


class Resize(Block):
    """Resize to (w, h) = size (ref transforms.py Resize)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        x = _as_nd(x)
        if isinstance(self._size, int):
            if self._keep:
                return _image.resize_short(x, self._size, self._interp)
            w = h = self._size
        else:
            w, h = self._size
        return _image.imresize(x, w, h, self._interp)


class CenterCrop(Block):
    """Center-crop to size, upsampling if needed
    (ref transforms.py CenterCrop)."""

    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interp = interpolation

    def forward(self, x):
        out, _ = _image.center_crop(_as_nd(x), self._size, self._interp)
        return out


class RandomResizedCrop(Block):
    """Random area/aspect crop resized to size
    (ref transforms.py RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = tuple(scale)
        self._ratio = tuple(ratio)
        self._interp = interpolation

    def forward(self, x):
        out, _ = _image.random_size_crop(_as_nd(x), self._size,
                                         self._scale, self._ratio,
                                         self._interp)
        return out


class _AugBlock(Block):
    """Adapter: run one mx.image Augmenter as a gluon transform."""

    def __init__(self, aug):
        super().__init__()
        self._aug = aug

    def forward(self, x):
        return self._aug(_as_nd(x))


class RandomFlipLeftRight(_AugBlock):
    def __init__(self):
        super().__init__(_image.HorizontalFlipAug(0.5))


class RandomFlipTopBottom(_AugBlock):
    def __init__(self):
        super().__init__(_image.VerticalFlipAug(0.5))


class RandomBrightness(_AugBlock):
    def __init__(self, brightness):
        super().__init__(_image.BrightnessJitterAug(brightness))


class RandomContrast(_AugBlock):
    def __init__(self, contrast):
        super().__init__(_image.ContrastJitterAug(contrast))


class RandomSaturation(_AugBlock):
    def __init__(self, saturation):
        super().__init__(_image.SaturationJitterAug(saturation))


class RandomHue(_AugBlock):
    def __init__(self, hue):
        super().__init__(_image.HueJitterAug(hue))


class RandomColorJitter(_AugBlock):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        augs = _image.ColorJitterAug(brightness, contrast, saturation)
        if hue:
            augs = _image.RandomOrderAug(
                [augs, _image.HueJitterAug(hue)])
        super().__init__(augs)


class RandomLighting(_AugBlock):
    def __init__(self, alpha):
        super().__init__(_image.LightingAug(
            alpha,
            eigval=np.asarray([55.46, 4.794, 1.148], np.float32),
            eigvec=np.asarray([[-0.5675, 0.7192, 0.4009],
                               [-0.5808, -0.0045, -0.8140],
                               [-0.5836, -0.6948, 0.4203]], np.float32)))
