"""Datasets (reference python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

import os

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset: __getitem__ + __len__
    (reference data/dataset.py:29)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        """Return a dataset with ``fn(x)`` applied to each sample."""
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        """Apply ``fn`` to only the first element of each sample."""
        return self.transform(_TransformFirstClosure(fn), lazy)


class SimpleDataset(Dataset):
    """Wrap any list-like into a Dataset (reference data/dataset.py:75)."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class ArrayDataset(Dataset):
    """Zip of array-likes (reference data/dataset.py:95)."""

    def __init__(self, *args):
        assert len(args) > 0, "Needs at least 1 arrays"
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                "All arrays must have the same length; array[0] has length " \
                "%d while array[%d] has %d." % (self._length, i, len(data))
            from ...ndarray import NDArray
            import numpy as np
            if isinstance(data, NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(data[idx] for data in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO (.rec) file
    (reference data/dataset.py:125); requires the .idx file."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
