"""Dataset abstractions for gluon data pipelines (behavioral parity:
python/mxnet/gluon/data/dataset.py — Dataset/SimpleDataset/ArrayDataset/
RecordFileDataset with the same transform semantics)."""
from __future__ import annotations

import os

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Random-access collection of samples: ``__getitem__`` + ``__len__``."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        """Map ``fn`` over samples.  Lazy by default (applied per access);
        ``lazy=False`` materialises the whole mapped dataset now."""
        mapped = _MappedDataset(self, fn)
        if lazy:
            return mapped
        return SimpleDataset([mapped[i] for i in range(len(mapped))])

    def transform_first(self, fn, lazy=True):
        """Map ``fn`` over only the first field of each sample (the usual
        image-not-label case)."""
        return self.transform(_FirstFieldTransform(fn), lazy)


class SimpleDataset(Dataset):
    """View any indexable sequence as a Dataset."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _MappedDataset(Dataset):
    """Lazy element-wise transform; tuple samples are splatted into ``fn``."""

    def __init__(self, source, fn):
        self._source = source
        self._fn = fn

    def __len__(self):
        return len(self._source)

    def __getitem__(self, idx):
        sample = self._source[idx]
        return self._fn(*sample) if isinstance(sample, tuple) \
            else self._fn(sample)


class _FirstFieldTransform:
    """Picklable closure: apply ``fn`` to field 0, pass the rest through."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, first, *rest):
        return (self._fn(first), *rest) if rest else self._fn(first)


class ArrayDataset(Dataset):
    """Zip one or more equal-length array-likes; single-array datasets yield
    bare elements, multi-array datasets yield tuples."""

    def __init__(self, *arrays):
        if not arrays:
            raise ValueError("Needs at least 1 arrays")
        from ...ndarray import NDArray
        self._length = len(arrays[0])
        self._fields = []
        for i, arr in enumerate(arrays):
            if len(arr) != self._length:
                raise ValueError(
                    f"All arrays must have the same length; array[0] has "
                    f"length {self._length} while array[{i}] has {len(arr)}.")
            if isinstance(arr, NDArray) and arr.ndim == 1:
                arr = arr.asnumpy()
            self._fields.append(arr)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        row = tuple(field[idx] for field in self._fields)
        return row[0] if len(row) == 1 else row


class RecordFileDataset(Dataset):
    """Raw records from a RecordIO pair (``file.rec`` + ``file.idx``)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        index_path = os.path.splitext(filename)[0] + ".idx"
        self._record = MXIndexedRecordIO(index_path, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
