"""DataLoader (reference python/mxnet/gluon/data/dataloader.py:26-96).

TPU-native worker model: the reference forks worker *processes* and ships
batches through CPU shared memory because Python-side decode contends with
the GIL-bound training loop. Here decode/augment is numpy (releases the
GIL in practice) and device transfer is jax's async host→HBM copy, so
``num_workers`` maps to a thread pool prefetching whole batches — no
pickle/shared-memory round-trip, same overlap.
"""
from __future__ import annotations

import numpy as np

from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py default_batchify_fn)."""
    from ... import ndarray as nd
    from ...ndarray import NDArray
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return nd.array(arr)


class DataLoader:
    """Loads batches from a Dataset (reference dataloader.py:26)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None or
              last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, int(num_workers))

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        # thread-pool prefetch: keep num_workers batches in flight
        from concurrent.futures import ThreadPoolExecutor
        import collections
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            pending = collections.deque()
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._num_workers * 2):
                    pending.append(pool.submit(self._make_batch, next(it)))
            except StopIteration:
                pass
            while pending:
                yield pending.popleft().result()
                try:
                    pending.append(pool.submit(self._make_batch, next(it)))
                except StopIteration:
                    pass

    def __len__(self):
        return len(self._batch_sampler)
