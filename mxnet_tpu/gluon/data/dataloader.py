"""DataLoader (reference python/mxnet/gluon/data/dataloader.py:26-96).

Worker model: ``num_workers > 0`` forks worker PROCESSES (reference
parity: dataloader.py:26-96 + cpu_shared_storage_manager.h) — each
worker batchifies on its own interpreter (no GIL contention with the
training loop) and ships the batch back through POSIX shared memory
(multiprocessing.shared_memory), one copy host-side; the parent's
``nd.array`` wrap is the same host→HBM copy every batch pays. Pure-numpy
augmentation that releases the GIL can instead use ``thread_pool=True``
(the round-3 thread-pool prefetcher — cheaper startup, no pickling).
"""
from __future__ import annotations

import numpy as np

from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def _np_batchify(data):
    """Worker-side batchify to plain numpy (device arrays cannot cross a
    process boundary; the parent wraps to NDArray after reassembly)."""
    first = data[0]
    if isinstance(first, tuple):
        return tuple(_np_batchify(list(x)) for x in zip(*data))
    if isinstance(first, (list,)):
        return [_np_batchify(list(x)) for x in zip(*data)]
    arr = np.stack([np.asarray(
        x.asnumpy() if hasattr(x, "asnumpy") else x) for x in data])
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


class _NdLeaf:
    """Marks a transported array that must rebuild as an NDArray (vs a
    user batchify_fn's plain numpy, which must stay numpy)."""
    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr


def _shm_export(obj, shms):
    """Replace array leaves with shared-memory descriptors."""
    from multiprocessing import shared_memory
    was_nd = isinstance(obj, _NdLeaf)
    if was_nd:
        obj = obj.arr
    if isinstance(obj, np.ndarray):
        shm = shared_memory.SharedMemory(create=True,
                                         size=max(obj.nbytes, 1))
        view = np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)
        view[...] = obj
        shms.append(shm)
        return ("__shm__", shm.name, obj.shape, obj.dtype.str, was_nd)
    if isinstance(obj, tuple):
        return tuple(_shm_export(x, shms) for x in obj)
    if isinstance(obj, list):
        return [_shm_export(x, shms) for x in obj]
    return obj


def _shm_import(obj):
    """Rebuild array leaves from shared-memory descriptors (copying out,
    then releasing the segment); _NdLeaf-tagged ones become NDArrays."""
    from multiprocessing import shared_memory
    if isinstance(obj, tuple) and len(obj) == 5 and obj[0] == "__shm__":
        _, name, shape, dtype, was_nd = obj
        shm = shared_memory.SharedMemory(name=name)
        try:
            arr = np.ndarray(shape, np.dtype(dtype), buffer=shm.buf).copy()
        finally:
            shm.close()
            shm.unlink()
        if was_nd:
            from ... import ndarray as nd
            return nd.array(arr, dtype=arr.dtype)
        return arr
    if isinstance(obj, tuple):
        return tuple(_shm_import(x) for x in obj)
    if isinstance(obj, list):
        return [_shm_import(x) for x in obj]
    return obj


def _worker_loop(dataset, batchify_fn, task_q, res_q):
    """Worker process body: pull (seq, indices), push (seq, shm batch).
    The dataset rides the fork — no per-batch pickling of samples."""
    while True:
        task = task_q.get()
        if task is None:
            return
        seq, indices = task
        try:
            if batchify_fn is None:
                # default batchify yields NDArrays — tag every leaf
                batch = _tag_nd(_np_batchify([dataset[i] for i in indices]))
            else:
                batch = batchify_fn([dataset[i] for i in indices])
                batch = _to_numpy_tree(batch)
            shms = []
            desc = _shm_export(batch, shms)
            res_q.put((seq, desc, None))
            for shm in shms:       # parent owns the segments now
                shm.close()
                # the PARENT unlinks after copying out; drop this
                # process' resource-tracker claim or its exit handler
                # warns about the already-removed segment
                try:
                    from multiprocessing import resource_tracker
                    resource_tracker.unregister(shm._name, "shared_memory")
                except Exception:
                    pass
        except Exception as e:     # surface worker errors in the parent
            import traceback
            res_q.put((seq, None, "%s\n%s" % (e, traceback.format_exc())))


def _to_numpy_tree(obj):
    """Device arrays can't cross the process boundary: NDArray leaves
    become _NdLeaf-tagged numpy (rebuilt as NDArray in the parent); a
    user batchify's plain numpy stays numpy on both sides."""
    if hasattr(obj, "asnumpy"):
        return _NdLeaf(np.asarray(obj.asnumpy()))
    if isinstance(obj, tuple):
        return tuple(_to_numpy_tree(x) for x in obj)
    if isinstance(obj, list):
        return [_to_numpy_tree(x) for x in obj]
    return obj


def _tag_nd(obj):
    if isinstance(obj, np.ndarray):
        return _NdLeaf(obj)
    if isinstance(obj, tuple):
        return tuple(_tag_nd(x) for x in obj)
    if isinstance(obj, list):
        return [_tag_nd(x) for x in obj]
    return obj


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py default_batchify_fn)."""
    from ... import ndarray as nd
    from ...ndarray import NDArray
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return nd.array(arr)


class DataLoader:
    """Loads batches from a Dataset (reference dataloader.py:26).

    ``num_workers > 0`` forks worker processes (shared-memory batch
    transport, reference parity). Worker code must stay host-side
    (numpy/PIL): forking a process whose accelerator runtime is
    initialized is safe only as long as the children never touch the
    device — the same constraint the reference has with CUDA. Datasets
    whose __getitem__ runs device ops should use ``thread_pool=True``
    instead."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, thread_pool=False):
        self._dataset = dataset
        self._thread_pool = bool(thread_pool)
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None or
              last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, int(num_workers))

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        if self._thread_pool:
            yield from self._iter_threads()
        else:
            yield from self._iter_processes()

    def _iter_threads(self):
        # thread-pool prefetch: keep num_workers batches in flight
        from concurrent.futures import ThreadPoolExecutor
        import collections
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            pending = collections.deque()
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._num_workers * 2):
                    pending.append(pool.submit(self._make_batch, next(it)))
            except StopIteration:
                pass
            while pending:
                yield pending.popleft().result()
                try:
                    pending.append(pool.submit(self._make_batch, next(it)))
                except StopIteration:
                    pass

    def _iter_processes(self):
        """Fork num_workers processes; batches return through shared
        memory, yielded strictly in sampler order (reference
        dataloader.py _MultiWorkerIter)."""
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        task_q = ctx.Queue()
        res_q = ctx.Queue()
        user_bfn = (None if self._batchify_fn is default_batchify_fn
                    else self._batchify_fn)
        workers = [ctx.Process(target=_worker_loop,
                               args=(self._dataset, user_bfn, task_q, res_q),
                               daemon=True)
                   for _ in range(self._num_workers)]
        for w in workers:
            w.start()
        try:
            it = iter(self._batch_sampler)
            sent = recvd = 0
            buffered = {}
            for _ in range(self._num_workers * 2):
                try:
                    task_q.put((sent, next(it)))
                    sent += 1
                except StopIteration:
                    break
            import queue as _queue
            while recvd < sent:
                while recvd not in buffered:
                    try:
                        seq, desc, err = res_q.get(timeout=5.0)
                    except _queue.Empty:
                        # a worker that died without enqueueing an error
                        # (segfault, OOM-kill) would otherwise hang this
                        # loop forever — poll liveness while waiting
                        dead = [w for w in workers if not w.is_alive()]
                        if dead:
                            raise RuntimeError(
                                f"DataLoader worker process(es) died "
                                f"unexpectedly (exitcodes "
                                f"{[w.exitcode for w in dead]}); "
                                f"batch {recvd} never arrived")
                        continue
                    if err is not None:
                        raise RuntimeError("DataLoader worker failed: %s"
                                           % err)
                    buffered[seq] = desc
                desc = buffered.pop(recvd)
                recvd += 1
                try:
                    task_q.put((sent, next(it)))
                    sent += 1
                except StopIteration:
                    pass
                yield _shm_import(desc)
        finally:
            for _ in workers:
                task_q.put(None)
            for w in workers:
                w.join(timeout=5)
                if w.is_alive():
                    w.terminate()
            # release every undelivered shm segment (out-of-order ones
            # buffered locally AND stragglers still in the queue) so an
            # error or early generator close leaks nothing in /dev/shm
            for desc in buffered.values():
                try:
                    _shm_import(desc)
                except Exception:
                    pass
            buffered.clear()
            try:
                while True:
                    _, desc, _err = res_q.get_nowait()
                    if desc is not None:
                        _shm_import(desc)
            except Exception:
                pass

    def __len__(self):
        return len(self._batch_sampler)
