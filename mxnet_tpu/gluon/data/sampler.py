"""Index samplers for DataLoader (behavioral parity:
python/mxnet/gluon/data/sampler.py — same classes, same ``last_batch``
policies)."""
from __future__ import annotations

import numpy as np

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler"]

_LAST_BATCH_POLICIES = ("keep", "discard", "rollover")


class Sampler:
    """Iterable over sample indices with a known length."""

    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        raise NotImplementedError


class _RangeSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __len__(self):
        return self._length


class SequentialSampler(_RangeSampler):
    """Indices 0..length-1 in order."""

    def __iter__(self):
        yield from range(self._length)


class RandomSampler(_RangeSampler):
    """A fresh uniform permutation of 0..length-1 each epoch."""

    def __iter__(self):
        yield from np.random.permutation(self._length).tolist()


class BatchSampler(Sampler):
    """Group an index sampler into batch-sized lists.

    ``last_batch`` controls the final partial batch: ``'keep'`` yields it
    short, ``'discard'`` drops it, ``'rollover'`` saves it to lead the next
    epoch.
    """

    def __init__(self, sampler, batch_size, last_batch="keep"):
        if last_batch not in _LAST_BATCH_POLICIES:
            raise ValueError(
                f"last_batch must be one of 'keep', 'discard', or "
                f"'rollover', but got {last_batch}")
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        pending = self._prev
        self._prev = []
        for idx in self._sampler:
            pending.append(idx)
            if len(pending) == self._batch_size:
                yield pending
                pending = []
        if not pending:
            return
        if self._last_batch == "keep":
            yield pending
        elif self._last_batch == "rollover":
            self._prev = pending
        # 'discard': drop the remainder

    def __len__(self):
        n, b = len(self._sampler), self._batch_size
        if self._last_batch == "keep":
            return -(-n // b)
        if self._last_batch == "discard":
            return n // b
        return (n + len(self._prev)) // b  # rollover
