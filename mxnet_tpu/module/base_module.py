"""BaseModule: the high-level train / score / predict interface.

API parity with the reference's ``python/mxnet/module/base_module.py``
(``fit`` :399, ``score`` :168, ``predict`` :264) — same signatures, same
log-line shapes — but the engine underneath is different and the loop is
built for it.  On TPU each ``forward_backward``+``update`` is ONE fused XLA
program whose dispatch returns immediately (the result arrays are futures);
the only host-blocking points are metric readback and data staging.  The
epoch loop here is therefore organised around a one-step-lookahead
``_Prefetcher`` (host decodes/stages batch N+1 while the device runs step N)
and metrics that read back only at callback boundaries, keeping the device
queue full instead of replaying the reference's synchronous
compute→wait→update sequence.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as _np

from .. import io as io_mod
from .. import metric as metric_mod
from .. import telemetry as _telemetry
from ..initializer import Uniform
from ..model import BatchEndParam
from ..ndarray.ndarray import concatenate

__all__ = ["BaseModule"]

# per-step wall time of the fit loop body (dispatch + staging + metric
# bookkeeping — NOT device completion, which is async; bench.py --mode
# fit reports device-independent launch counters for that reason)
FIT_STEP_MS = _telemetry.REGISTRY.histogram(
    "fit_step_ms", "wall time of one fit-loop step (host side)",
    unit="ms")


def _callbacks(spec):
    """Normalise a callback spec (None | callable | list) to a tuple."""
    if spec is None:
        return ()
    if callable(spec):
        return (spec,)
    return tuple(spec)


def _ensure_metric(m):
    return m if isinstance(m, metric_mod.EvalMetric) else metric_mod.create(m)


def _trim_pad(arrays, pad):
    """Drop the iterator's pad rows from each output array."""
    if not pad:
        return list(arrays)
    return [a[: a.shape[0] - pad] for a in arrays]


def _check_input_names(symbol, names, typename, throw):
    """Warn/raise when a user-declared input name is absent from the graph."""
    known = set(symbol.list_arguments()) | set(symbol.list_auxiliary_states())
    for name in names:
        if name in known:
            continue
        msg = (f"You created Module with Module(..., {typename}_names={names}) "
               f"but input with name '{name}' is not found in "
               f"symbol.list_arguments().")
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


class _Prefetcher:
    """One-step-lookahead wrapper over a DataIter.

    ``advance()`` returns the staged batch and immediately pulls + stages the
    next one, so host-side staging (including sparse row-id pulls via
    ``module.prepare``) overlaps the device executing the current step.
    ``peek_done`` is True once the underlying iterator is exhausted, letting
    the loop know the batch in hand is the last.
    """

    def __init__(self, data_iter, module, sparse_row_id_fn=None):
        self._it = iter(data_iter)
        self._mod = module
        self._row_fn = sparse_row_id_fn
        self._staged = None
        self._pull()

    def _pull(self):
        try:
            self._staged = next(self._it)
        except StopIteration:
            self._staged = None

    @property
    def has_next(self):
        return self._staged is not None

    def advance(self):
        batch = self._staged
        self._pull()
        return batch

    def stage_next(self):
        """Stage the already-fetched lookahead batch (sparse row pulls etc.).
        Called after the current step's ``update`` so staged rows reflect
        post-update parameter values."""
        if self._staged is not None:
            self._mod.prepare(self._staged, sparse_row_id_fn=self._row_fn)


class BaseModule:
    """Abstract train/eval surface; concrete modules implement the
    bind/forward/backward/update primitives and inherit the loops."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # ------------------------------------------------------------------
    # training
    # ------------------------------------------------------------------
    def forward_backward(self, data_batch):
        """One fused fwd+bwd dispatch (a single XLA program downstream)."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def fit_step(self, data_batch, eval_metric=None):
        """One training step: the eager pair — a fused fwd+bwd dispatch,
        then the optimizer/kvstore update. Subclasses may fuse further
        (Module routes eligible configs through module/fused_fit.py as
        ONE donated program) and return True to signal the whole step —
        including device-side metric accumulation — ran as a single
        launch, making the loop's ``update_metric`` call a no-op."""
        self.forward_backward(data_batch)
        self.update()
        return False

    def _fit_sync(self):
        """Block until in-flight device work completes — the bounded-
        async-depth hook behind ``MXNET_FIT_SYNC_EVERY`` (overridden by
        Module; a no-op for modules without device-resident state)."""
        pass

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None, checkpoint_every=None,
            checkpoint_prefix=None):
        """Train for ``num_epoch`` epochs.  Signature parity with the
        reference ``fit`` (base_module.py:399); loop structure is the
        prefetched design described in the module docstring.

        ``checkpoint_every``/``checkpoint_prefix`` (env:
        ``MXNET_CHECKPOINT_EVERY`` / ``MXNET_CHECKPOINT_PREFIX``) arm
        mx.checkpoint (docs/CHECKPOINT.md): every N steps the COMPLETE
        training state — params, optimizer state, error-feedback
        residuals, RNG, lr position — snapshots at the step boundary
        and commits on a background writer; the loop blocks only for
        the device→host copy, the fused-step zero-retrace guarantee is
        untouched, and a SIGTERM triggers an emergency synchronous save
        plus graceful drain before ``fit`` returns."""
        if num_epoch is None:
            raise ValueError("please specify number of epochs")

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        train_metric = _ensure_metric(eval_metric)
        val_metric = validation_metric or train_metric
        on_batch = _callbacks(batch_end_callback)
        on_epoch = _callbacks(epoch_end_callback)

        ckpt = self._make_checkpointer(checkpoint_every, checkpoint_prefix)
        # pod health (straggler exchange) + hang watchdog — both no-ops
        # unless armed (multi-process world / env; docs/OBSERVABILITY.md)
        health = _telemetry.PodHealthMonitor.maybe_create(self.logger)
        # pod metrics aggregation + SLO rule evaluation on the merged
        # view (multi-process world, MXNET_SENTINEL_EVERY, or installed
        # sentinel rules — docs/OBSERVABILITY.md)
        sentinel = _telemetry.PodMetricsAggregator.maybe_create(
            self.logger)
        watchdog = None
        if float(os.environ.get("MXNET_WATCHDOG_FACTOR", "0") or 0) > 0:
            watchdog = _telemetry.Watchdog("fit")
        try:
            for epoch in range(begin_epoch, num_epoch):
                preempted = self._run_train_epoch(
                    epoch, train_data, train_metric, monitor, on_batch,
                    sparse_row_id_fn, ckpt, health, watchdog, sentinel)
                if preempted:
                    self.logger.warning(
                        "Epoch[%d] preempted — emergency checkpoint "
                        "committed, stopping fit", epoch)
                    return
                # Sync params out of the device-side optimizer state once
                # per epoch so epoch callbacks (checkpointing) see current
                # values.
                arg_now, aux_now = self.get_params()
                self.set_params(arg_now, aux_now)
                for cb in on_epoch:
                    cb(epoch, self.symbol, arg_now, aux_now)
                if eval_data is not None:
                    scores = self.score(
                        eval_data, val_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch)
                    for name, val in scores:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)
                train_data.reset()
        finally:
            if watchdog is not None:
                watchdog.disarm()
            if ckpt is not None:
                ckpt.close()        # drain pending writes, restore signals

    def _make_checkpointer(self, checkpoint_every, checkpoint_prefix):
        """A CheckpointManager when step checkpointing is requested (arg
        or env), else None."""
        every = checkpoint_every if checkpoint_every is not None \
            else int(os.environ.get("MXNET_CHECKPOINT_EVERY", "0") or 0)
        if not every:
            if checkpoint_prefix \
                    or os.environ.get("MXNET_CHECKPOINT_PREFIX"):
                self.logger.warning(
                    "checkpoint prefix given but checkpoint_every/"
                    "MXNET_CHECKPOINT_EVERY is unset — checkpointing is "
                    "NOT armed")
            return None
        prefix = checkpoint_prefix \
            or os.environ.get("MXNET_CHECKPOINT_PREFIX") or "checkpoint"
        from ..checkpoint import CheckpointManager
        return CheckpointManager(prefix, module=self, every=every,
                                 logger=self.logger)

    def _run_train_epoch(self, epoch, train_data, train_metric, monitor,
                         on_batch, sparse_row_id_fn, ckpt=None,
                         health=None, watchdog=None, sentinel=None):
        """One epoch: keep the device queue full, read metrics back only
        at callback boundaries. With the fused fit step active, the loop
        body performs ZERO blocking host syncs — metrics accumulate on
        device and step N+1 dispatches while step N executes; the
        ``MXNET_FIT_SYNC_EVERY`` env var (0 = unbounded, the default)
        bounds how many steps may be in flight. ``ckpt`` (a
        CheckpointManager) ticks at each step boundary; returns True
        when the epoch stopped early on a preemption (emergency
        checkpoint already committed). ``health`` (PodHealthMonitor)
        exchanges per-rank step times on its cadence; ``watchdog``
        heartbeats around each step (both host-only; mx.trace spans
        bracket the step and its children when tracing is enabled —
        docs/OBSERVABILITY.md)."""
        t0 = time.time()
        train_metric.reset()
        flow = _Prefetcher(train_data, self, sparse_row_id_fn)
        sync_every = int(os.environ.get("MXNET_FIT_SYNC_EVERY", "0") or 0)
        tracing = _telemetry.tracing
        nbatch = 0
        while flow.has_next:
            # the fit.step span parents every child opened inside —
            # prefetch data-wait (flow.advance may block on the input
            # pipeline), fused dispatch, kvstore push/pull — so one
            # step renders as one subtree. FIT_STEP_MS keeps its
            # historical meaning (dispatch + staging + bookkeeping,
            # data-wait excluded — that one has io_data_wait_ms).
            with tracing.span("fit.step", epoch=epoch, step=nbatch) as sp:
                batch = flow.advance()
                if monitor is not None:
                    monitor.tic()
                t_step = time.perf_counter()
                if watchdog is not None:
                    watchdog.begin()
                # fit_step enqueues async XLA work (one donated program
                # when fused); while the device runs, the host stages
                # the (already-fetched) next batch. update_metric is a
                # no-op for batches the fused step already folded on
                # device.
                self.fit_step(batch, train_metric)
                flow.stage_next()
                self.update_metric(train_metric, batch.label)
                step_ctx = getattr(sp, "context", None)
            if watchdog is not None:
                watchdog.end()
            # telemetry (all host-side, nothing enters traced code):
            # step-time histogram, flight-recorder cadence, chrome-trace
            # step marker — each a no-op-cheap call when idle
            step_ms = (time.perf_counter() - t_step) * 1e3
            FIT_STEP_MS.observe(step_ms)
            if health is not None:
                health.step(step_ms)
            if sentinel is not None:
                # an exchange step first drains the pipeline through the
                # EXISTING sync boundary (_fit_sync publishes the
                # in-launch sentinel scalars), so the shipped snapshot
                # carries fresh numerics; off-cadence steps pay one
                # attribute check
                if sentinel.due():
                    self._fit_sync()
                sentinel.step()
            _telemetry.RECORDER.tick()
            _telemetry.mark_step(nbatch)
            if monitor is not None:
                monitor.toc_print()
            if on_batch:
                info = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                     eval_metric=train_metric, locals=None)
                for cb in on_batch:
                    cb(info)
            nbatch += 1
            if sync_every and nbatch % sync_every == 0:
                self._fit_sync()
            # checkpoint tick LAST: the step (and its metric fold) is
            # fully dispatched, so the snapshot sees post-step handles.
            # Its span parents under the (already-ended) step span —
            # parent links are ids, a closed parent is fine.
            if ckpt is not None:
                with tracing.span("checkpoint.tick", parent=step_ctx):
                    if ckpt.tick(epoch=epoch):
                        return True
        # epoch boundary: the one scheduled metric readback of the epoch
        for name, val in train_metric.get_name_value():
            self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
        self.logger.info("Epoch[%d] Time cost=%.3f", epoch, time.time() - t0)
        return False

    # ------------------------------------------------------------------
    # evaluation / inference
    # ------------------------------------------------------------------
    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """Run ``eval_data`` through forward and accumulate ``eval_metric``."""
        if not (self.binded and self.params_initialized):
            raise RuntimeError("score() requires bind() + init_params()")
        if reset:
            eval_data.reset()
        eval_metric = _ensure_metric(eval_metric)
        eval_metric.reset()
        on_batch = _callbacks(batch_end_callback)

        seen = 0
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.prepare(batch, sparse_row_id_fn=sparse_row_id_fn)
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            for cb in on_batch:
                cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                 eval_metric=eval_metric, locals=None))
            seen += 1
        for cb in _callbacks(score_end_callback):
            cb(BatchEndParam(epoch=epoch, nbatch=seen,
                             eval_metric=eval_metric, locals=None))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """Yield ``(outputs, nbatch, batch)`` per forward pass (pad-trimmed)."""
        if not (self.binded and self.params_initialized):
            raise RuntimeError("iter_predict() requires bind() + init_params()")
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            yield _trim_pad(self.get_outputs(), batch.pad), nbatch, batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Forward every batch; by default concatenate per-output across
        batches (and unwrap a single output, matching the reference)."""
        if not (self.binded and self.params_initialized):
            raise RuntimeError("predict() requires bind() + init_params()")
        if isinstance(eval_data, _np.ndarray) or hasattr(eval_data, "shape"):
            eval_data = io_mod.NDArrayIter(eval_data,
                                           batch_size=eval_data.shape[0])
        if reset:
            eval_data.reset()

        per_batch = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            per_batch.append([o.copy() for o in
                              _trim_pad(self.get_outputs(), batch.pad)])
        if not per_batch:
            return per_batch
        if not merge_batches:
            return per_batch
        widths = {len(outs) for outs in per_batch}
        if len(widths) != 1:
            raise ValueError("Cannot merge batches: different number of outputs")
        merged = [concatenate([outs[i] for outs in per_batch])
                  for i in range(widths.pop())]
        if len(merged) == 1 and not always_output_list:
            return merged[0]
        return merged

    # ------------------------------------------------------------------
    # parameter persistence
    # ------------------------------------------------------------------
    def save_params(self, fname):
        """Save current params in the reference's ``arg:``/``aux:`` layout."""
        from .. import ndarray as nd
        arg_params, aux_params = self.get_params()
        blob = {f"arg:{k}": v for k, v in arg_params.items()}
        blob.update({f"aux:{k}": v for k, v in aux_params.items()})
        nd.save(fname, blob)

    def load_params(self, fname):
        """Load params saved by :meth:`save_params` (reference layout)."""
        from .. import ndarray as nd
        arg_params, aux_params = {}, {}
        for key, value in nd.load(fname).items():
            kind, _, name = key.partition(":")
            if kind == "arg":
                arg_params[name] = value
            elif kind == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # ------------------------------------------------------------------
    # abstract surface (implemented by Module / BucketingModule / ...)
    # ------------------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def install_monitor(self, mon):
        raise NotImplementedError

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError
